"""Device tree-kernel parity: byte-identical summaries vs the oracle.

The convergence oracle pattern (SURVEY.md §4): generate sequenced tree op
logs through the mock runtime's fuzz loop, replay them through both the
CPU oracle and the vmapped device fold, and compare canonical digests.
"""

import random

import pytest

from fluidframework_tpu.dds.tree import ROOT_ID, SharedTree
from fluidframework_tpu.ops.tree_kernel import (
    TreeDocInput,
    oracle_fallback_summary,
    replay_tree_batch,
)
from fluidframework_tpu.testing.mocks import (
    MockContainerRuntimeFactory,
    channel_log,
)


def oracle_summary(doc: TreeDocInput):
    return oracle_fallback_summary(doc)


def run_fuzz_doc(seed, steps=80, n_clients=3, with_moves=True):
    """Drive a fuzzed multi-client session; return the sequenced log and
    final window, the exact catch-up work item."""
    rng = random.Random(seed)
    factory = MockContainerRuntimeFactory()
    trees = []
    for i in range(n_clients):
        rt = factory.create_client(f"client{i}")
        trees.append(rt.attach(SharedTree("tree")))
    for _ in range(steps):
        t = rng.choice(trees)
        roll = rng.random()
        try:
            if roll < 0.4:
                field = rng.choice(["a", "b"])
                parents = [ROOT_ID] + [
                    c for c in t.children(ROOT_ID, "a")
                ]
                parent = rng.choice(parents)
                kids = t.children(parent, field)
                nested = (
                    {"kids": [t.build("leaf", value=rng.randint(0, 9))]}
                    if rng.random() < 0.3 else None
                )
                t.insert(parent, field, rng.randint(0, len(kids)),
                         [t.build("n", value=rng.randint(0, 99),
                                  fields=nested)])
            elif roll < 0.55:
                field = rng.choice(["a", "b"])
                kids = t.children(ROOT_ID, field)
                if kids:
                    t.remove(rng.choice(kids))
            elif roll < 0.7:
                field = rng.choice(["a", "b"])
                kids = t.children(ROOT_ID, field)
                if kids:
                    t.set_value(
                        rng.choice(kids),
                        rng.choice([rng.randint(0, 99), "s", None]),
                    )
            elif roll < 0.85 and with_moves:
                src = rng.choice(["a", "b"])
                kids = t.children(ROOT_ID, src)
                if kids:
                    nid = rng.choice(kids)
                    if rng.random() < 0.3 and len(kids) > 1:
                        dest_parent = rng.choice(
                            [k for k in kids if k != nid]
                        )
                        dest = (dest_parent, "kids")
                    else:
                        dest = (ROOT_ID, rng.choice(["a", "b"]))
                    n_dest = len([
                        k for k in t.children(*dest) if k != nid
                    ])
                    t.move([nid], dest[0], dest[1],
                           rng.randint(0, n_dest))
            else:
                factory.process_some_messages(rng.randint(1, 4))
        except (KeyError, ValueError):
            pass
    factory.process_all_messages()
    log = channel_log(factory, "tree")
    final_seq = factory.sequencer.seq
    final_msn = factory.sequencer.min_seq
    return factory, trees, log, final_seq, final_msn


@pytest.mark.parametrize("seed", [1, 2, 3, 17, 55, 301])
def test_device_matches_oracle_fuzz(seed):
    factory, trees, log, final_seq, final_msn = run_fuzz_doc(seed)
    doc = TreeDocInput(
        doc_id="tree", ops=log, final_seq=final_seq, final_msn=final_msn
    )
    (device,) = replay_tree_batch([doc])
    oracle = oracle_summary(doc)
    assert device.digest() == oracle.digest()
    # And both equal the live replicas' summaries.
    assert device.digest() == trees[0].summarize().digest()


def test_device_batch_many_docs():
    docs = []
    oracles = []
    for seed in range(8):
        _f, _t, log, fs, fm = run_fuzz_doc(seed + 1000, steps=40,
                                           with_moves=(seed % 2 == 0))
        doc = TreeDocInput("tree", ops=log, final_seq=fs, final_msn=fm)
        docs.append(doc)
        oracles.append(oracle_summary(doc))
    results = replay_tree_batch(docs)
    for device, oracle in zip(results, oracles):
        assert device.digest() == oracle.digest()


def test_device_from_base_summary():
    """Catch-up from a mid-stream summary + tail, the north-star shape."""
    factory, trees, log, final_seq, final_msn = run_fuzz_doc(77, steps=60)
    # Split: summary at the midpoint op, tail after.
    mid = len(log) // 2
    base_replica = SharedTree("tree")
    for msg in log[:mid]:
        base_replica.process(msg, local=False)
    base = base_replica.summarize()
    doc = TreeDocInput(
        "tree", ops=log[mid:], base_summary=base,
        final_seq=final_seq, final_msn=final_msn,
    )
    (device,) = replay_tree_batch([doc])
    oracle = oracle_summary(doc)
    assert device.digest() == oracle.digest()
    assert device.digest() == trees[0].summarize().digest()


def test_revive_falls_back_to_oracle():
    factory = MockContainerRuntimeFactory()
    rt = factory.create_client("c0")
    t = rt.attach(SharedTree("tree"))
    (nid,) = t.insert(ROOT_ID, "", 0, [t.build("n", value="v")])
    factory.process_all_messages()
    t.remove(nid)
    factory.process_all_messages()
    _seq, _c, cs = t.edit_manager.trunk[-1]
    t.undo_changeset(cs)  # produces a revive edit
    factory.process_all_messages()
    log = channel_log(factory, "tree")
    doc = TreeDocInput("tree", ops=log,
                       final_seq=factory.sequencer.seq,
                       final_msn=factory.sequencer.min_seq)
    (device,) = replay_tree_batch([doc])
    assert device.digest() == t.summarize().digest()


def test_empty_and_noop_docs():
    doc = TreeDocInput("empty", ops=[])
    (device,) = replay_tree_batch([doc])
    oracle = oracle_summary(doc)
    assert device.digest() == oracle.digest()
    assert replay_tree_batch([]) == []


def test_deterministic_across_runs():
    """Same batch twice → bitwise-equal results (SURVEY.md §5 race
    detection equivalent: determinism checks)."""
    _f, _t, log, fs, fm = run_fuzz_doc(5, steps=50)
    doc = TreeDocInput("tree", ops=log, final_seq=fs, final_msn=fm)
    d1 = replay_tree_batch([doc])[0].digest()
    d2 = replay_tree_batch([doc])[0].digest()
    assert d1 == d2


def test_limbo_rescue_survives_purge_summary_and_device():
    """A node moved into a subtree whose tombstone then EXPIRES must stay
    rescuable by id: the purge detaches it to limbo instead of deleting it,
    summaries carry a "limbo" section so reloads converge, the device fold
    applies the rescue move exactly, and a limbo-carrying base summary
    routes the warm fold to the oracle (fuzz-found divergence class)."""
    import json

    from fluidframework_tpu.dds.tree import SharedTree
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def op(seq, min_seq, edits):
        return SequencedMessage(
            seq=seq, client_id="c0", client_seq=seq, ref_seq=seq - 1,
            min_seq=min_seq, type=MessageType.OP, contents={"edits": edits},
        )

    def ins(nid, parent, field, val):
        return {"kind": "insert", "parent": parent, "field": field,
                "anchor": None,
                "content": [{"id": nid, "type": "n", "value": val}]}

    log = [
        op(1, 0, [ins("A", "", "a", 1)]),
        op(2, 0, [ins("B", "", "a", 2)]),
        op(3, 0, [{"kind": "move", "ids": ["B"], "parent": "A",
                   "field": "kids", "anchor": None,
                   "prev": [["B", "", "a", None]]}]),
        op(4, 0, [{"kind": "remove", "ids": ["A"]}]),
        op(5, 4, [ins("C", "", "a", 3)]),  # A expires -> B detached (limbo)
        op(6, 4, [ins("D", "", "a", 4)]),
        op(7, 4, [{"kind": "move", "ids": ["B"], "parent": "", "field": "a",
                   "anchor": None,
                   "prev": [["B", "A", "kids", None]]}]),  # the rescue
    ]

    live = SharedTree("t")
    for m in log:
        live.process(m, local=False)
    final = live.summarize()
    header = json.loads(final.blob_bytes("header"))
    assert any(
        n["id"] == "B" for n in header["fields"]["a"]
    ), "rescued node must be visible again"

    # mid-stream summary carries the limbo section; reload + tail converges
    mid = SharedTree("t")
    for m in log[:6]:
        mid.process(m, local=False)
    mid_summary = mid.summarize()
    mid_obj = json.loads(mid_summary.blob_bytes("header"))
    assert [n["id"] for n in mid_obj["limbo"]] == ["B"]
    reloaded = SharedTree("t")
    reloaded.load(mid_summary)
    for m in log[6:]:
        reloaded.process(m, local=False)
    assert reloaded.summarize().digest() == final.digest()

    # device: cold fold exact; warm fold from the limbo base falls back
    [dev] = replay_tree_batch(
        [TreeDocInput("t", ops=log, final_seq=7, final_msn=4)]
    )
    assert dev.digest() == final.digest()
    stats = {}
    [warm] = replay_tree_batch(
        [TreeDocInput("t", ops=log[6:], base_summary=mid_summary,
                      final_seq=7, final_msn=4)],
        stats=stats,
    )
    assert warm.digest() == final.digest()
    # Per-reason fallback accounting (ISSUE 14 satellite): the opaque
    # total survives, joined by WHY the doc left the device path.
    assert stats == {"fallback_docs": 1, "fallback_base_limbo": 1}


def test_deep_tree_fuzz_device_parity():
    """Deep tree fuzz (120 steps, 4 clients — the purge-race shape that
    diverged before the limbo hardening; 400-seed sweeps ran clean
    offline) with device parity and fallback accounting."""
    for seed in (40007, 40045, 40060, 40100, 40200):
        factory, trees, log, fs, fm = run_fuzz_doc(
            seed, steps=120, n_clients=4
        )
        assert len({t.summarize().digest() for t in trees}) == 1
        doc = TreeDocInput("tree", ops=log, final_seq=fs, final_msn=fm)
        stats = {}
        [device] = replay_tree_batch([doc], stats=stats)
        assert device.digest() == trees[0].summarize().digest(), seed


def test_summarize_wider_min_seq_emits_limbo():
    """summarize(min_seq) beyond the channel's advanced window must surface
    kept descendants of newly-expiring tombstones as limbo — identical to a
    replica whose window actually advanced (review-found: the container
    summarizes channels with ITS min_seq, which can exceed the channel's)."""
    import json

    from fluidframework_tpu.dds.tree import ROOT_ID, SharedTree
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def op(seq, min_seq, edits):
        return SequencedMessage(
            seq=seq, client_id="c0", client_seq=seq, ref_seq=seq - 1,
            min_seq=min_seq, type=MessageType.OP, contents={"edits": edits},
        )

    log = [
        op(1, 0, [{"kind": "insert", "parent": "", "field": "a",
                   "anchor": None,
                   "content": [{"id": "A", "type": "n", "value": 1}]}]),
        op(2, 0, [{"kind": "insert", "parent": "", "field": "a",
                   "anchor": None,
                   "content": [{"id": "B", "type": "n", "value": 2}]}]),
        op(3, 0, [{"kind": "move", "ids": ["B"], "parent": "A",
                   "field": "kids", "anchor": None,
                   "prev": [["B", "", "a", None]]}]),
        op(4, 0, [{"kind": "remove", "ids": ["A"]}]),
    ]
    idle = SharedTree("t")
    for m in log:
        idle.process(m, local=False)  # window never advances (min_seq 0)
    wide = idle.summarize(min_seq=4)  # container-wide MSN exceeds channel's
    obj = json.loads(wide.blob_bytes("header"))
    assert [n["id"] for n in obj.get("limbo", [])] == ["B"]

    advanced = SharedTree("t")
    for m in log[:3]:
        advanced.process(m, local=False)
    advanced.process(
        SequencedMessage(seq=4, client_id="c0", client_seq=4, ref_seq=3,
                         min_seq=0, type=MessageType.OP,
                         contents={"edits": [{"kind": "remove",
                                              "ids": ["A"]}]}),
        local=False,
    )
    advanced.advance(5, 4)  # the purge actually runs
    assert advanced.summarize(min_seq=4).digest() == wide.digest()


# -- hardware-rule regression net: the tree family gets the same Mosaic
# block-rule pin + non-divisible-bucket parity coverage that
# test_pallas_fold.py gives the merge-tree family. --


def _fuzz_doc_input(seed, steps):
    _factory, _trees, log, final_seq, final_msn = run_fuzz_doc(
        seed, steps=steps)
    return TreeDocInput(doc_id="tree", ops=log, final_seq=final_seq,
                        final_msn=final_msn)


def test_tree_buckets_satisfy_mosaic_block_rule():
    """Mirror of test_pallas_fold.test_padded_block_dims_satisfy_mosaic_
    rule for the tree family: every device-plane bucket the packer
    derives (N and T from tree_buckets, C inside pack_tree_batch) is a
    power-of-two ladder value at or above its floor — hence divisible
    by the 8-row sublane unit — and covers the per-doc used-row counts
    it was sized from (pads extend, never truncate)."""
    from fluidframework_tpu.ops.tree_kernel import (
        pack_tree_batch,
        tree_buckets,
    )

    docs = [_fuzz_doc_input(1400 + i, steps)
            for i, steps in enumerate((4, 25, 60, 110))]
    for k in range(1, len(docs) + 1):
        sub = docs[:k]
        N, T = tree_buckets(sub)
        state, edits, meta = pack_tree_batch(sub)
        C = state.head.shape[1]
        for bucket, floor in ((N, 16), (T, 16), (C, 8)):
            assert bucket >= floor and bucket % 8 == 0
            # Power-of-two ladder: a finite, stable set of jit shapes.
            assert bucket & (bucket - 1) == 0, bucket
        # The allocated planes use exactly the derived buckets ...
        assert state.next.shape == (k, N)
        assert edits.kind.shape == (k, T)
        # ... and every used-row count fits inside its bucket.
        assert int(meta["n_nodes"].max()) <= N
        assert int(meta["n_cont"].max()) <= C
        assert int(meta["t_rows"].max()) <= T


def test_tree_parity_on_nondivisible_buckets():
    """Mirror of test_pallas_fold.test_pallas_fold_parity_on_
    nondivisible_buckets: full digest parity on a batch whose natural
    buckets genuinely violate the (8, 128) lane rule — a doc count that
    is not a multiple of 8 and node/edit buckets that are not multiples
    of 128 — so pad rows must be provably inert, not accidentally
    aligned away."""
    from fluidframework_tpu.ops.tree_kernel import tree_buckets

    docs = [_fuzz_doc_input(1500 + i, steps=18) for i in range(11)]
    N, T = tree_buckets(docs)
    assert len(docs) % 8 != 0, "D accidentally 8-aligned"
    assert N % 128 != 0, f"N={N} accidentally 128-aligned"
    assert T % 128 != 0, f"T={T} accidentally 128-aligned"
    summaries = replay_tree_batch(docs)
    for doc, device in zip(docs, summaries):
        assert device.digest() == oracle_summary(doc).digest()
