"""fluidproc (ISSUE 12): out-of-process serving tier.

Three layers of coverage:

1. Engine/logic tests against THREAD-backend clusters (same RPC, same
   per-shard on-disk logs, "kill" = abandon-without-another-stamp): the
   routing proxy, epoch-fenced failover with adoption from the dead
   shard's log, lazy adoption, the wrongShard redirect, live migration
   (~1/N movers, byte-identical logs, retirement), and a crash point at
   EVERY migration step.
2. REAL-process tests (``ProcShard``): kill -9 mid-traffic converging
   byte-identical to the fault-free single-service oracle, SIGSTOP hang
   detection, SIGTERM drain-and-seal with restart-resumes-contiguous,
   and the per-shard ``stats`` RPC.
3. The fluidscale swarm driven out-of-proc: the 10³-client tier-1 smoke
   (oracle-verified) and the ``slow``-marked 10⁵ scenario matrix.
"""

import dataclasses
import json
import os
import threading
import time

import pytest

from fluidframework_tpu.drivers.network_driver import (
    NetworkDocumentServiceFactory, _RpcClient)
from fluidframework_tpu.protocol.messages import (DocRelocatedError,
                                                  MessageType, NackError,
                                                  RawOperation)
from fluidframework_tpu.protocol.wire import encode_raw_operation
from fluidframework_tpu.service.frontdoor import (FrontDoor,
                                                  MigrationAborted,
                                                  ProcShard)
from fluidframework_tpu.service.oplog import shard_log_path
from fluidframework_tpu.service.orderer import LocalOrderingService
from fluidframework_tpu.service.sharding import rendezvous_score
from fluidframework_tpu.testing.faults import (FaultInjector, FaultPlan,
                                               FaultPoint, SCHEDULED_SITES,
                                               SITES)


def _op(client, i, ref):
    return RawOperation(client_id=client, client_seq=i + 1, ref_seq=ref,
                        type=MessageType.OP, contents={"i": i})


def _drive_tier(door, docs, n_ops, start=0, refs=None, progress=None):
    """Submit ``n_ops`` ops per doc through the front door (one logical
    writer per doc — the per-doc op stream is deterministic), riding
    failovers via a bounded retry loop.  ``progress`` (a one-element
    list) exposes the completed op index to a concurrent killer."""
    refs = refs if refs is not None else {}
    factory = NetworkDocumentServiceFactory(port=door.port)
    rpc = factory._rpc
    try:
        if start == 0:
            for d in docs:
                rpc.request("create_document", {"doc": d})
                rpc.request("connect", {"doc": d, "client": f"w-{d}"})
                refs[d] = rpc.request("head", {"doc": d})
        for i in range(start, start + n_ops):
            for d in docs:
                for _attempt in range(10):
                    try:
                        result = rpc.request("submit", {
                            "doc": d,
                            "op": encode_raw_operation(
                                _op(f"w-{d}", i, refs[d]))})
                        if result is None:
                            # Deduped resend: the first attempt LANDED
                            # before the kill and the response died with
                            # the process — the op is durable; read the
                            # head back (client_seq dedup is the whole
                            # point of safe resends).
                            refs[d] = rpc.request("head", {"doc": d})
                        else:
                            refs[d] = result["sequenceNumber"]
                        break
                    except (ConnectionError, OSError, NackError):
                        time.sleep(0.05)
                else:
                    raise AssertionError(f"{d}: op {i} never landed")
            if progress is not None:
                progress[0] = i
    finally:
        factory.close()
    return refs


def _oracle_logs(docs, n_ops):
    """The fault-free single-service oracle: identical per-doc op
    streams through ONE in-proc orderer; returns {doc: wire dicts}."""
    service = LocalOrderingService()
    out = {}
    for d in docs:
        endpoint = service.create_document(d)
        endpoint.connect(f"w-{d}")
        ref = endpoint.head_seq
        for i in range(n_ops):
            ref = endpoint.submit(_op(f"w-{d}", i, ref)).seq
        from fluidframework_tpu.protocol.wire import encode_sequenced_message

        out[d] = [encode_sequenced_message(m) for m in endpoint.deltas()]
    return out


def _tier_logs(door, docs):
    return {d: door._forward_doc("deltas", {"doc": d}) for d in docs}


# -- faultline sites ----------------------------------------------------------


def test_proc_fault_sites_registered_and_scheduled():
    assert SITES["proc.kill"] == ("kill",)
    assert SITES["proc.hang"] == ("hang",)
    assert "proc.kill" in SCHEDULED_SITES and "proc.hang" in SCHEDULED_SITES
    FaultPoint("proc.kill", "kill", at=7, doc="d").validate()
    with pytest.raises(ValueError):
        FaultPoint("proc.kill", "hang").validate()


def test_proc_fault_points_fire_via_due_with_coverage_accounting():
    plan = FaultPlan(points=(
        FaultPoint("proc.kill", "kill", at=5, shard="s1"),
        FaultPoint("proc.hang", "hang", at=3, doc="d0"),
    ))
    injector = FaultInjector(plan)
    assert injector.due("proc.kill", 4) == []
    hung = injector.due("proc.hang", 3)
    assert [p.site for p in hung] == ["proc.hang"]
    killed = injector.due("proc.kill", 9)
    assert [p.shard for p in killed] == ["s1"]
    assert injector.unfired() == []
    # an unexecutable kill rolls its mark back for the coverage oracle
    injector.mark_unfired(killed[0])
    assert [p.site for p in injector.unfired()] == ["proc.kill"]
    assert injector.snapshot() == {"proc.hang:hang": 1,
                                   "proc.kill:kill": 0}


# -- thread-backend cluster logic ---------------------------------------------


@pytest.fixture()
def cluster(tmp_path):
    door = FrontDoor(str(tmp_path / "proc"), n_shards=4,
                     spawn="thread").start()
    yield door
    door.close()


DOCS = [f"doc-{i}" for i in range(10)]


def test_frontdoor_routes_proxies_and_reports_stats(cluster):
    _drive_tier(cluster, DOCS[:4], 5)
    heads = cluster.heads(DOCS[:4])
    assert all(h == 6 for h in heads.values()), heads  # JOIN + 5 ops
    client = _RpcClient("127.0.0.1", cluster.port)
    try:
        stats = client.request("stats", {})
    finally:
        client.close()
    assert sorted(stats["shards"]) == cluster.router.shard_ids()
    assert sum(s["ops"] for s in stats["shards"].values()
               if "ops" in s) == 24
    per_shard_docs = sum(s["docs"] for s in stats["shards"].values())
    assert per_shard_docs == 4
    assert stats["epoch"] == cluster.epoch


def test_failover_converges_byte_identical_to_oracle(cluster):
    refs = _drive_tier(cluster, DOCS, 4)
    victim = cluster._route_probe(DOCS[0])[0]
    old_epoch = cluster.epoch
    affected = cluster.fail_shard(victim)
    assert DOCS[0] in affected
    # traffic continues across the whole doc set, same logical streams
    _drive_tier(cluster, DOCS, 4, start=4, refs=refs)
    assert cluster.epoch != old_epoch  # fence epoch bumped on survivors
    for d in DOCS:
        assert cluster._forward_doc("log_contiguous", {"doc": d}), d
    assert _tier_logs(cluster, DOCS) == _oracle_logs(DOCS, 8)
    # the dead shard's documents all re-owned off the corpse
    for d in DOCS:
        assert cluster._route_probe(d)[0] != victim


def test_lazy_adoption_on_first_touch(cluster):
    _drive_tier(cluster, DOCS, 3)
    victim = cluster._route_probe(DOCS[0])[0]
    victims_docs = [d for d in DOCS
                    if cluster._route_probe(d)[0] == victim]
    cluster.fail_shard(victim)
    with cluster._route_lock:
        orphaned = dict(cluster._orphans)
    # no subscriptions in this harness → nothing adopted eagerly
    assert sorted(orphaned) == sorted(victims_docs)
    assert all(src == victim for src in orphaned.values())
    # first touch imports the span from the dead shard's log
    head = cluster.heads([victims_docs[0]])[victims_docs[0]]
    assert head == 4  # JOIN + 3 ops, nothing lost
    with cluster._route_lock:
        assert victims_docs[0] not in cluster._orphans


def test_wrong_shard_redirect_roundtrip(cluster):
    _drive_tier(cluster, DOCS[:2], 2)
    doc = DOCS[0]
    sid = cluster._route_probe(doc)[0]
    handle = cluster._shard(sid)
    handle.request("retire_doc", {"doc": doc})
    # direct-to-shard clients get the typed redirect...
    direct = _RpcClient(handle.addr[0], handle.addr[1])
    try:
        with pytest.raises(DocRelocatedError):
            direct.request("head", {"doc": doc})
    finally:
        direct.close()
    # ...while the front door re-resolves: un-retire by re-adopting the
    # doc (import path clears retirement), which _forward_doc triggers
    # by re-routing after the wrongShard answer.
    with cluster._route_lock:
        cluster._orphans[doc] = sid
    head = cluster.heads([doc])[doc]
    assert head == 3


def test_live_migration_moves_docs_byte_identically(cluster):
    refs = _drive_tier(cluster, DOCS, 4)
    before = {d: cluster._route_probe(d)[0] for d in DOCS}
    result = cluster.add_shard("shard90")
    after = {d: cluster._route_probe(d)[0] for d in DOCS}
    movers = [d for d in DOCS if after[d] == "shard90"]
    assert sorted(result["moved"]) == sorted(movers)
    # rendezvous property: ONLY docs moving to the new shard moved
    for d in DOCS:
        if d not in movers:
            assert after[d] == before[d], d
    # traffic continues on every doc (migrated included), then compare
    _drive_tier(cluster, DOCS, 4, start=4, refs=refs)
    for d in DOCS:
        assert cluster._forward_doc("log_contiguous", {"doc": d}), d
    assert _tier_logs(cluster, DOCS) == _oracle_logs(DOCS, 8)
    # the source copies are RETIRED: a stale direct route cannot fork
    if movers:
        src = before[movers[0]]
        handle = cluster._shard(src)
        direct = _RpcClient(handle.addr[0], handle.addr[1])
        try:
            with pytest.raises(DocRelocatedError):
                direct.request("submit", {
                    "doc": movers[0],
                    "op": encode_raw_operation(
                        _op(f"w-{movers[0]}", 99, 0))})
        finally:
            direct.close()


def _movers_for(door, docs, new_sid):
    future = door.router.alive() + [new_sid]
    return [d for d in docs
            if max(future, key=lambda s: (rendezvous_score(d, s), s))
            == new_sid]


@pytest.mark.parametrize("step,who", [
    ("freeze", "src"), ("transfer", "src"), ("import", "src"),
    ("flip", "src"), ("resume", "src"),
    ("import", "dst"), ("flip", "dst"), ("resume", "dst"),
])
def test_migration_crash_points_converge(tmp_path, step, who):
    """Kill a shard process at EVERY migration step, source and target:
    source deaths degrade to failover + retry (the doc still ends up
    migrated, logs never fork); a pre-import target death aborts the
    expansion with the frozen doc THAWED — it never left; a post-import
    target death converges through the failover/adoption path (the
    target's log already holds the live span) whether the expansion
    aborts or joins a corpse the next touch fails over."""
    door = FrontDoor(str(tmp_path / "proc"), n_shards=4,
                     spawn="thread").start()
    try:
        refs = _drive_tier(door, DOCS, 3)
        new_sid = "shard91"
        movers = _movers_for(door, DOCS, new_sid)
        assert movers, "need at least one migrating doc for a crash test"
        target_doc = movers[0]
        src_sid = door._route_probe(target_doc)[0]
        fired = []

        def hook(at_step, doc):
            if at_step == step and doc == target_doc and not fired:
                fired.append((at_step, doc))
                victim = src_sid if who == "src" else new_sid
                door._shards[victim].kill()

        door.set_crash_hook(hook)
        if who == "dst" and step == "import":
            # pre-import target death: clean abort, nothing moved
            with pytest.raises(MigrationAborted):
                door.add_shard(new_sid)
            assert new_sid not in door.router.shard_ids()
        elif who == "dst":
            # post-import target death: the span is durable in the
            # target's log — the expansion may abort (re-orphaning the
            # flipped docs) or complete with a corpse; either way the
            # traffic below must converge via failover/adoption.
            try:
                door.add_shard(new_sid)
            except MigrationAborted:
                pass
        else:
            result = door.add_shard(new_sid)
            assert target_doc in result["moved"]
            assert door._route_probe(target_doc)[0] == new_sid
        door.set_crash_hook(None)
        assert fired, "crash hook never fired"
        # the tier converges: same logical streams continue everywhere
        _drive_tier(door, DOCS, 3, start=3, refs=refs)
        for d in DOCS:
            assert door._forward_doc("log_contiguous", {"doc": d}), d
        assert _tier_logs(door, DOCS) == _oracle_logs(DOCS, 6)
    finally:
        door.close()


def test_refresh_doc_after_own_upload_still_ingests_peer_records(tmp_path):
    """Regression (caught by the 10⁵ drill re-record): the refresh scan
    memo must only advance inside refresh_doc itself.  An instance's OWN
    upload grows the shared file past records OTHER processes appended
    since its last scan — snapshotting the size there marked those as
    seen, and the adopted doc's summary chain silently vanished."""
    from fluidframework_tpu.drivers.file_driver import FileSummaryStorage
    from fluidframework_tpu.protocol.summary import SummaryTree

    root = str(tmp_path / "summaries")
    a = FileSummaryStorage(root)
    b = FileSummaryStorage(root)
    # A (another process's instance) appends doc X's chain...
    ha = a.upload("doc-x", SummaryTree().add_blob("b", b"peer"), 5)
    # ...then B uploads for ITS OWN doc before ever refreshing
    b.upload("doc-y", SummaryTree().add_blob("b", b"own"), 3)
    # B adopts doc X: refresh must still ingest A's record
    b.refresh_doc("doc-x")
    assert b.head("doc-x") is not None
    assert b.read_commit(b.head("doc-x")).tree == ha
    tree, ref_seq = b.latest("doc-x")
    assert ref_seq == 5 and tree.digest() == ha


def test_last_live_shard_is_unfailable_before_the_kill(tmp_path):
    """Review pin: the last live shard is refused BEFORE the SIGKILL —
    a missed heartbeat on a sole survivor must degrade to a stall, not
    a self-inflicted total outage (in-proc kill_shard parity)."""
    door = FrontDoor(str(tmp_path / "proc"), n_shards=2,
                     spawn="thread").start()
    try:
        refs = _drive_tier(door, DOCS[:4], 2)
        first, second = door.router.alive()
        door.fail_shard(first)
        with pytest.raises(RuntimeError):
            door.fail_shard(second)
        # the survivor was NOT killed: traffic continues
        assert door.router.alive() == [second]
        assert door._shard(second).alive()
        _drive_tier(door, DOCS[:4], 2, start=2, refs=refs)
        assert _tier_logs(door, DOCS[:4]) == _oracle_logs(DOCS[:4], 4)
    finally:
        door.close()


def test_adopting_nothing_durable_clears_the_orphan_without_looping(
        tmp_path):
    """Review pin: a created-but-empty document (no ops, no summary)
    that died with its shard adopts as 'nothing durable' — the orphan
    mark clears (no error loop) and the document simply no longer
    exists, exactly the in-proc failover outcome."""
    door = FrontDoor(str(tmp_path / "proc"), n_shards=3,
                     spawn="thread").start()
    client = _RpcClient("127.0.0.1", door.port)
    try:
        client.request("create_document", {"doc": "empty-doc"})
        victim = door._route_probe("empty-doc")[0]
        # give the victim a SECOND doc with real history: its span must
        # adopt fine while the empty doc resolves to nothing
        full_doc = next(d for d in DOCS
                        if door._route_probe(d)[0] == victim)
        _drive_tier(door, [full_doc], 3)
        door.fail_shard(victim)
        heads = door.heads(["empty-doc", full_doc])
        assert heads == {"empty-doc": 0, full_doc: 4}
        with door._route_lock:
            assert "empty-doc" not in door._orphans  # cleared, no loop
            assert full_doc not in door._orphans
        assert not client.request("has_document", {"doc": "empty-doc"})
        client.request("create_document", {"doc": "empty-doc"})  # reusable
    finally:
        client.close()
        door.close()


def test_tick_executes_proc_kill_and_hang_points(tmp_path):
    plan = FaultPlan(points=(
        FaultPoint("proc.kill", "kill", doc=DOCS[0], at=5),
        FaultPoint("proc.hang", "hang", doc=DOCS[1], at=2),
    ))
    injector = FaultInjector(plan)
    door = FrontDoor(str(tmp_path / "proc"), n_shards=4, spawn="thread",
                     faults=injector, hang_detect_ticks=2).start()
    try:
        _drive_tier(door, DOCS[:4], 2)
        hang_victim = door._route_probe(DOCS[1])[0]
        assert door.tick(1) == []
        door.tick(2)  # SIGSTOP fires; not detected yet
        assert hang_victim not in door.router.dead()
        kill_victim = door._route_probe(DOCS[0])[0]
        affected = door.tick(5)  # kill executes AND the hang is detected
        assert kill_victim in door.router.dead()
        assert hang_victim in door.router.dead()
        assert affected
        assert injector.unfired() == []
        assert injector.snapshot() == {"proc.hang:hang": 1,
                                       "proc.kill:kill": 1}
    finally:
        door.close()


# -- REAL processes -----------------------------------------------------------


def test_sigkill_mid_traffic_converges_byte_identical(tmp_path):
    """THE acceptance bar: kill -9 a real shard process mid-traffic; the
    tier converges byte-identical (per-doc wire logs, contiguous seqs)
    to the fault-free single-service oracle fed the same logical op
    streams — the same bar the in-proc failover meets."""
    door = FrontDoor(str(tmp_path / "proc"), n_shards=4, spawn="proc",
                     request_timeout=5.0).start()
    try:
        docs = [f"doc-{i}" for i in range(6)]
        refs = _drive_tier(door, docs, 4)
        victim_sid = door._route_probe(docs[0])[0]
        victim = door._shard(victim_sid)
        progress = [0]
        errors = []

        def assassinate():
            # kill -9 once the writer loop below is provably mid-stream
            try:
                deadline = time.monotonic() + 30
                while progress[0] < 10 and time.monotonic() < deadline:
                    time.sleep(0.005)
                victim.proc.kill()
            except Exception as exc:  # pragma: no cover - diagnostics
                errors.append(exc)

        killer = threading.Thread(target=assassinate, daemon=True)
        killer.start()
        _drive_tier(door, docs, 16, start=4, refs=refs,
                    progress=progress)
        killer.join(timeout=30)
        assert not errors
        assert victim.proc.poll() is not None, "victim survived kill -9"
        assert victim_sid in door.router.dead(), \
            "transport-error path never detected the kill"
        for d in docs:
            assert door._forward_doc("log_contiguous", {"doc": d}), d
        assert _tier_logs(door, docs) == _oracle_logs(docs, 20)
        stats = door.stats()
        assert stats["fences"] == 1
        assert victim_sid not in stats["alive"]
    finally:
        door.close()


def test_sigstop_hang_is_detected_and_shot(tmp_path):
    door = FrontDoor(str(tmp_path / "proc"), n_shards=3, spawn="proc",
                     request_timeout=4.0).start()
    try:
        docs = [f"doc-{i}" for i in range(4)]
        refs = _drive_tier(door, docs, 3)
        victim_sid = door._route_probe(docs[0])[0]
        victim = door._shard(victim_sid)
        victim.hang()  # SIGSTOP: alive but silent
        assert victim.proc.poll() is None
        failed = door.poll_shards()  # heartbeat sweep: ping times out
        assert failed == [victim_sid]
        # shoot-the-node: the stopped process was SIGKILLed BEFORE its
        # documents were re-owned — it can never wake up and write
        assert victim.proc.poll() is not None
        _drive_tier(door, docs, 3, start=3, refs=refs)
        assert _tier_logs(door, docs) == _oracle_logs(docs, 6)
    finally:
        door.close()


def test_sigterm_drains_seals_and_restart_resumes(tmp_path):
    """The graceful-shutdown satellite: SIGTERM racing a large group
    commit drains the in-flight batch and seals the per-shard log —
    the durable file holds NO duplicate seq lines and strictly
    contiguous seqs, and a restart over the same directory resumes the
    sequence exactly where the seal left it."""
    base = str(tmp_path / "proc")
    handle = ProcShard("s0", base)
    handle.connect()
    doc = "drain-doc"
    handle.request("create_document", {"doc": doc})
    handle.request("connect", {"doc": doc, "client": "w"})
    head = handle.request("head", {"doc": doc})
    ops = [encode_raw_operation(_op("w", i, head)) for i in range(2000)]
    outcome = {}

    def big_batch():
        try:
            outcome["result"] = handle.request(
                "submit_mixed", {"batches": {doc: ops}})
        except (ConnectionError, OSError) as exc:
            outcome["error"] = exc

    writer = threading.Thread(target=big_batch, daemon=True)
    writer.start()
    time.sleep(0.05)  # let the batch reach the server
    handle.proc.terminate()  # SIGTERM mid-group-commit
    handle.proc.wait(timeout=30)
    writer.join(timeout=30)
    # the sealed log: no duplicate lines, strictly contiguous seqs
    path = shard_log_path(base, "s0")
    seqs = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            rec = json.loads(line)
            if rec["doc"] == doc:
                seqs.append(rec["msg"]["sequenceNumber"])
    assert len(seqs) == len(set(seqs)), "duplicate lines in sealed log"
    assert seqs == list(range(1, len(seqs) + 1)), "seqs not contiguous"
    sealed_head = len(seqs)
    assert sealed_head >= 1  # the JOIN at minimum; usually the batch too
    # restart over the same directory: the sequence resumes contiguously
    handle2 = ProcShard("s0", base)
    handle2.connect()
    try:
        assert handle2.request("heads", {"docs": [doc]})[doc] == sealed_head
        result = handle2.request("submit", {
            "doc": doc,
            "op": encode_raw_operation(_op("w", 5000, sealed_head))})
        assert result["sequenceNumber"] == sealed_head + 1
        assert handle2.request("log_contiguous", {"doc": doc})
    finally:
        handle2.close()
        handle2.terminate()
    handle.close()


def test_draining_server_refuses_with_typed_nack(tmp_path):
    from fluidframework_tpu.service.shardhost import (ShardHost,
                                                      ShardHostServer)

    host = ShardHost("s0", str(tmp_path / "proc"))
    server = ShardHostServer(host, port=0)
    server.start_in_thread()
    rpc = _RpcClient("127.0.0.1", server.port)
    try:
        rpc.request("create_document", {"doc": "d"})
        server.draining = True
        assert rpc.request("ping", {}) == "pong"  # probes stay answered
        assert "shard" in rpc.request("stats", {})
        with pytest.raises(NackError) as err:
            rpc.request("submit", {
                "doc": "d", "op": encode_raw_operation(_op("w", 0, 0))})
        assert err.value.code == "shuttingDown"
        assert err.value.retry_after > 0
    finally:
        rpc.close()
        host.seal()


def test_per_shard_stats_rpc_over_the_wire(tmp_path):
    door = FrontDoor(str(tmp_path / "proc"), n_shards=2,
                     spawn="proc").start()
    try:
        _drive_tier(door, ["a-doc", "b-doc"], 3)
        client = _RpcClient("127.0.0.1", door.port)
        try:
            stats = client.request("stats", {})
        finally:
            client.close()
        shard_stats = stats["shards"]
        assert set(shard_stats) == set(door.router.shard_ids())
        pids = {s["pid"] for s in shard_stats.values()}
        assert len(pids) == 2 and os.getpid() not in pids, \
            "stats must come from the shard PROCESSES"
        heads = {}
        for s in shard_stats.values():
            heads.update(s["heads"])
        assert heads == {"a-doc": 4, "b-doc": 4}
    finally:
        door.close()


# -- the swarm against the process tier ---------------------------------------


def test_proc_swarm_smoke_oracle_verified(tmp_path):
    """ISSUE 12 satellite: the 10³-client scenario smoke against the
    REAL process tier — per-shard durable logs, batched ingress over the
    wire both hops — byte-identical to the in-proc single-shard oracle."""
    from fluidframework_tpu.testing.scenarios import (build_scenario,
                                                      oracle_spec,
                                                      run_swarm)

    spec = build_scenario("steady-typing", seed=12, clients=1000, docs=16,
                          shards=4)
    spec = dataclasses.replace(spec, out_of_proc=True, sample_every=8,
                               dir=str(tmp_path / "swarm"))
    result = run_swarm(spec)
    assert result.sequenced_ops > 1000
    twin = run_swarm(oracle_spec(spec, result))
    assert result.sampled_digests == twin.sampled_digests
    assert result.per_doc_head == twin.per_doc_head
    cluster = result.shard_stats["cluster"]
    assert sorted(cluster["shards"]) == [f"shard{i:02d}" for i in range(4)]
    assert sum(s.get("ops", 0) for s in cluster["shards"].values()) \
        == result.sequenced_ops
    # the live taps really relayed broadcast through the front door
    assert any(n > 0
               for n in result.shard_stats["tap_unique_frames"].values())


@pytest.mark.slow
def test_proc_swarm_failover_drill_100k():
    """Nightly: the failover drill at 10⁵ clients against real shard
    processes — a REAL SIGKILL mid-run at population scale, oracle- and
    replay-verified."""
    from fluidframework_tpu.testing.faults import FaultPlan, FaultPoint
    from fluidframework_tpu.testing.scenarios import (build_scenario,
                                                      oracle_spec,
                                                      run_swarm)

    spec = build_scenario("failover-drill", seed=12, clients=100_000,
                          docs=128, shards=4)
    total = sum(p.ticks for p in spec.phases)
    plan = FaultPlan(seed=12, points=(
        FaultPoint("proc.kill", "kill", doc="sw-0000", at=total // 2),))
    spec = dataclasses.replace(spec, out_of_proc=True, plan=plan)
    result = run_swarm(spec)
    assert result.kills, "the process kill never executed"
    twin = run_swarm(oracle_spec(spec, result))
    assert result.sampled_digests == twin.sampled_digests
    assert result.per_doc_head == twin.per_doc_head
    replay = run_swarm(spec)
    assert replay.identity() == result.identity()
