"""Op-level NACK with retryAfter (SURVEY.md §5 failure detection).

The service can refuse to sequence an op — throttling, or a ref_seq below
the collaboration window.  A nack is NOT a lost op: the runtime keeps the
encoded messages queued, the DeltaManager holds sends until retryAfter
elapses, and the next writable flush resends — optimistic local state
stays intact throughout and replicas converge.
"""

import time

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.protocol.messages import (
    MessageType,
    NackError,
    RawOperation,
)
from fluidframework_tpu.service import LocalOrderingService
from fluidframework_tpu.testing.load import LoadSpec, run_load


def _nack_first_n(n, retry_after=0.0):
    state = {"count": 0}

    def throttle(_client_id):
        state["count"] += 1
        if state["count"] <= n:
            return retry_after
        return None

    return throttle


def test_nacked_op_is_requeued_and_resent():
    service = LocalOrderingService(throttle=_nack_first_n(1))
    loader = Loader(LocalDocumentServiceFactory(service))
    a = loader.create("doc", "alice",
                      lambda rt: rt.create_datastore("ds").create_channel(
                          "sequence-tpu", "t"))
    text = a.runtime.get_datastore("ds").get_channel("t")
    text.insert_text(0, "held")       # first submit after connect: nacked
    assert a.delta_manager.nacks >= 1
    assert text.text == "held"        # optimistic state intact
    a.runtime.flush()                 # retry resends the SAME encoded op
    a.drain()
    assert service.oplog.get("doc")[-1].contents["ops"][0]["contents"] == \
        {"kind": "insert", "pos": 0, "text": "held"}

    fresh = loader.resolve("doc")
    assert fresh.runtime.get_datastore("ds").get_channel("t").text == "held"


def test_retry_after_holds_sends_until_elapsed():
    service = LocalOrderingService(throttle=_nack_first_n(1,
                                                          retry_after=0.15))
    loader = Loader(LocalDocumentServiceFactory(service))
    a = loader.create("doc", "alice",
                      lambda rt: rt.create_datastore("ds").create_channel(
                          "sequence-tpu", "t"))
    a.runtime.get_datastore("ds").get_channel("t").insert_text(0, "x")
    assert a.delta_manager.nacks == 1
    assert not a.delta_manager.can_send  # held by retryAfter
    a.runtime.flush()                    # no-op while held
    assert service.oplog.head("doc") == 1  # just the JOIN
    time.sleep(0.16)
    assert a.delta_manager.can_send
    a.runtime.flush()
    a.drain()
    assert service.oplog.head("doc") == 2


def test_ref_seq_below_window_is_nacked():
    service = LocalOrderingService()
    ep = service.create_document("doc")
    ep.connect("a")
    ep.connect("b")
    for i in range(1, 4):
        ep.submit(RawOperation(client_id="a", client_seq=i, ref_seq=3,
                               type=MessageType.OP, contents={"k": i}))
    ep.update_ref_seq("b", 5)  # window floor rises past an old view
    assert ep._orderer.sequencer.min_seq > 0
    with pytest.raises(NackError, match="below the collaboration window"):
        ep.submit(RawOperation(client_id="a", client_seq=9, ref_seq=0,
                               type=MessageType.OP, contents={"k": 9}))


def test_nack_crosses_the_wire_with_retry_after():
    from fluidframework_tpu.drivers.network_driver import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.service.server import OrderingServer

    srv = OrderingServer(
        LocalOrderingService(throttle=_nack_first_n(1, retry_after=2.5)),
        port=0,
    )
    srv.start_in_thread()
    factory = NetworkDocumentServiceFactory(port=srv.port)
    from fluidframework_tpu.runtime.container import ContainerRuntime

    seeded = ContainerRuntime()
    seeded.create_datastore("ds").create_channel("sequence-tpu", "t")
    svc = factory.create_document("doc", seeded.summarize())
    conn = svc.connection()
    conn.connect("alice")
    with pytest.raises(NackError) as exc:
        conn.submit(RawOperation(client_id="alice", client_seq=1, ref_seq=0,
                                 type=MessageType.OP, contents={}))
    assert exc.value.retry_after == 2.5
    factory.close()


def test_load_harness_converges_under_nack_fault_injection():
    result = run_load(LoadSpec(seed=11, clients=3, steps=120, nack_every=7))
    assert result.nacks_issued > 0, "fault injection must actually fire"
    assert result.final_clients >= 1
    assert len(result.summary_digest) == 64  # convergence asserted inside


def test_summarizer_backs_off_after_nacks():
    """Drive the PRODUCTION path: a scribe that nacks every summary makes
    the manager retry on the backoff cadence (4, then 8 ops later), not
    every op and not only at the full window."""
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.runtime.summarizer import (
        SummarizerOptions,
        SummaryManager,
    )
    from fluidframework_tpu.protocol.summary import SummaryStorage

    service = LocalOrderingService()
    ep = service.create_document("doc")
    rt = ContainerRuntime()
    text = rt.create_datastore("ds").create_channel("sequence-tpu", "t")
    rt.connect(ep, "summarizer")
    rt.drain()
    # A PRIVATE storage: uploads land here, so the service-side scribe
    # always nacks the announced handle as unknown.
    mgr = SummaryManager(rt, SummaryStorage(), "doc",
                         SummarizerOptions(ops_per_summary=50,
                                           nack_retry_ops=4))
    attempts = []
    orig = mgr.summarize_now

    def counting():
        attempts.append(rt.ref_seq)
        return orig()

    mgr.summarize_now = counting
    for i in range(90):
        text.insert_text(0, "x")
        rt.drain()
    scribe_nacks = service._orderers["doc"].scribe.nacks
    assert scribe_nacks >= 2, "scribe must have nacked summaries"
    assert mgr.consecutive_nacks >= 2
    assert len(attempts) >= 3
    # retries follow the widening backoff, not a hot loop
    gaps = [b - a for a, b in zip(attempts, attempts[1:])]
    assert all(g >= 4 for g in gaps), gaps
    assert any(g >= 8 for g in gaps[1:]), gaps


def test_stale_view_nack_triggers_rebase_reconnect():
    """A staleView nack (queued bytes referencing a view below the
    collaboration window) must not livelock resending identical bytes:
    the container pump reconnects, rebasing pending ops to a fresh view."""
    service = LocalOrderingService()
    loader = Loader(LocalDocumentServiceFactory(service))
    a = loader.create("doc", "alice",
                      lambda rt: rt.create_datastore("ds").create_channel(
                          "sequence-tpu", "t"))
    b = loader.resolve("doc", "bob")
    ta = a.runtime.get_datastore("ds").get_channel("t")
    ta.insert_text(0, "base")
    a.drain()
    b.drain()

    # Freeze alice's outbound by simulating an offline window: submit is
    # blocked so the op encodes at the CURRENT (soon stale) ref_seq.
    a.delta_manager.read_only = True
    try:
        ta.insert_text(4, "-late")
    except Exception:
        pass
    a.delta_manager.read_only = False
    # Window floor rises past alice's encoded ref while she is quiet.
    for i in range(3):
        b.runtime.get_datastore("ds").get_channel("t").insert_text(0, "z")
        b.drain()
    ep = service.endpoint("doc")
    ep.update_ref_seq("bob", ep.head_seq)
    ep.update_ref_seq("alice", ep.head_seq)
    # pump: flush gets nacked staleView -> drain reconnect-rebases
    for _ in range(6):
        a.runtime.flush()
        a.drain()
        b.drain()
    assert a.runtime.get_datastore("ds").get_channel("t").text ==         b.runtime.get_datastore("ds").get_channel("t").text
    assert "-late" in a.runtime.get_datastore("ds").get_channel("t").text
