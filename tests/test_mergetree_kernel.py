"""Device merge-tree kernel vs CPU oracle: byte-identical summaries.

The north-star acceptance gate (SURVEY.md §7 layer 4): fuzz-generated
SharedString op logs replayed through the device op-fold must produce the
exact canonical summary bytes of the oracle — same walk, same tie-breaks,
same overlap-removal bookkeeping, same normalization.
"""

import json

import pytest

from fluidframework_tpu.dds import SharedString
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    replay_mergetree_batch,
)
from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz
from fluidframework_tpu.testing.mocks import channel_log


def _kernel_inputs_from_fuzz(factory, doc_id="fuzz", base_records=None,
                             min_seq_exclusive=0):
    return MergeTreeDocInput(
        doc_id=doc_id,
        ops=channel_log(factory, "fuzz", min_seq_exclusive=min_seq_exclusive),
        base_records=base_records,
        final_seq=factory.sequencer.seq,
        final_msn=factory.sequencer.min_seq,
    )


@pytest.mark.parametrize("seed", range(8))
def test_mergetree_kernel_matches_oracle_on_fuzz_logs(seed):
    replicas, factory = run_fuzz(
        StringFuzzSpec(), seed=seed, n_clients=3, rounds=20
    )
    oracle = replicas[0].summarize()
    [summary] = replay_mergetree_batch([_kernel_inputs_from_fuzz(factory)])
    assert summary.digest() == oracle.digest(), (
        f"seed={seed}: kernel body "
        f"{summary.blob_bytes('body')!r} != oracle "
        f"{oracle.blob_bytes('body')!r}"
    )


def test_mergetree_kernel_batches_docs_of_different_sizes():
    docs, oracle_digests = [], []
    for seed in (50, 51, 52):
        replicas, factory = run_fuzz(
            StringFuzzSpec(), seed=seed, n_clients=2, rounds=6 + 4 * (seed % 3)
        )
        docs.append(_kernel_inputs_from_fuzz(factory, doc_id=f"d{seed}"))
        oracle_digests.append(replicas[0].summarize().digest())
    summaries = replay_mergetree_batch(docs)
    assert [s.digest() for s in summaries] == oracle_digests


def test_mergetree_kernel_replays_tail_from_base_summary():
    """The flagship catch-up shape: summary at seq S + op tail."""
    replicas, factory = run_fuzz(
        StringFuzzSpec(), seed=9, n_clients=3, rounds=16
    )
    full_ops = channel_log(factory, "fuzz")
    mid_seq = full_ops[len(full_ops) // 2].seq
    # Build the base summary by oracle catch-up to the midpoint.
    partial = SharedString("fuzz")
    for msg in full_ops:
        if msg.seq <= mid_seq:
            partial.process(msg, local=False)
    base_summary = partial.summarize()
    base_records = json.loads(base_summary.blob_bytes("body"))
    doc = MergeTreeDocInput(
        doc_id="fuzz",
        ops=[m for m in full_ops if m.seq > mid_seq],
        base_records=base_records,
        final_seq=factory.sequencer.seq,
        final_msn=factory.sequencer.min_seq,
    )
    [summary] = replay_mergetree_batch([doc])
    # Oracle continuation from the same summary must agree too.
    resumed = SharedString("fuzz")
    resumed.load(base_summary)
    for msg in full_ops:
        if msg.seq > mid_seq:
            resumed.process(msg, local=False)
    resumed.advance(factory.sequencer.seq, factory.sequencer.min_seq)
    assert summary.digest() == resumed.summarize().digest()


@pytest.mark.parametrize("seed", range(6))
def test_mergetree_kernel_with_interval_ops(seed):
    """Config #3 parity: logs containing interval ops replay through the
    device fold + host interval pass to oracle-identical bytes."""
    replicas, factory = run_fuzz(
        StringFuzzSpec(intervals=True), seed=900 + seed, n_clients=3, rounds=25
    )
    oracle = replicas[0].summarize()
    [summary] = replay_mergetree_batch([_kernel_inputs_from_fuzz(factory)])
    assert summary.digest() == oracle.digest(), (
        f"seed={seed}: kernel {summary.children.keys()} vs oracle "
        f"{oracle.children.keys()}"
    )


def test_interval_tail_from_base_summary():
    """Catch-up with a base summary carrying an intervals blob."""
    replicas, factory = run_fuzz(
        StringFuzzSpec(intervals=True), seed=42, n_clients=3, rounds=16
    )
    full_ops = channel_log(factory, "fuzz")
    mid_seq = full_ops[len(full_ops) // 2].seq
    partial = SharedString("fuzz")
    for msg in full_ops:
        if msg.seq <= mid_seq:
            partial.process(msg, local=False)
    base_summary = partial.summarize()
    base_records = json.loads(base_summary.blob_bytes("body"))
    try:
        base_intervals = json.loads(base_summary.blob_bytes("intervals"))
    except KeyError:
        base_intervals = None
    doc = MergeTreeDocInput(
        doc_id="fuzz",
        ops=[m for m in full_ops if m.seq > mid_seq],
        base_records=base_records,
        base_intervals=base_intervals,
        base_seq=partial.tree.current_seq,
        base_msn=partial.tree.min_seq,
        final_seq=factory.sequencer.seq,
        final_msn=factory.sequencer.min_seq,
    )
    [summary] = replay_mergetree_batch([doc])
    resumed = SharedString("fuzz")
    resumed.load(base_summary)
    for msg in full_ops:
        if msg.seq > mid_seq:
            resumed.process(msg, local=False)
    resumed.advance(factory.sequencer.seq, factory.sequencer.min_seq)
    assert summary.digest() == resumed.summarize().digest()


def test_summarize_refuses_inflight_interval_ops():
    from fluidframework_tpu.testing import MockContainerRuntimeFactory

    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(SharedString("s"))
    a.insert_text(0, "text")
    factory.process_all_messages()
    a.add_interval(0, 2)
    with pytest.raises(RuntimeError, match="in-flight interval ops"):
        a.summarize()
    factory.process_all_messages()
    a.summarize()  # fine once sequenced


def test_insert_with_none_prop_value_matches_kernel():
    """Regression: a None prop value on insert means 'absent' on both paths."""
    from fluidframework_tpu.testing import MockContainerRuntimeFactory

    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(SharedString("s"))
    a.insert_text(0, "hello", props={"k": None, "m": 2})
    factory.process_all_messages()
    [dev] = replay_mergetree_batch(
        [
            MergeTreeDocInput(
                "s",
                channel_log(factory, "s"),
                final_seq=factory.sequencer.seq,
                final_msn=factory.sequencer.min_seq,
            )
        ]
    )
    assert dev.digest() == a.summarize().digest()
    assert json.loads(a.summarize().blob_bytes("body"))[0]["p"] == {"m": 2}


def test_mergetree_kernel_empty_doc_and_noop_padding():
    doc = MergeTreeDocInput(doc_id="empty", ops=[], final_seq=0, final_msn=0)
    [summary] = replay_mergetree_batch([doc])
    fresh = SharedString("empty")
    assert summary.digest() == fresh.summarize().digest()


def test_export_widths_agree_and_widen_roundtrips():
    """The int16 export (doc-rebased tstart, remapped sentinels) must widen
    back to exactly the int32 export, and both must extract to the same
    canonical summaries (the i16 path halves the device→host transfer — the
    measured pipeline bottleneck)."""
    import numpy as np

    from fluidframework_tpu.ops.mergetree_kernel import (
        pack_mergetree_batch,
        replay_export,
        summaries_from_export,
        widen_export,
    )

    docs = []
    for seed in (70, 71, 72, 73):
        replicas, factory = run_fuzz(
            StringFuzzSpec(), seed=seed, n_clients=3, rounds=8
        )
        docs.append(_kernel_inputs_from_fuzz(factory, doc_id=f"w{seed}"))
    state, ops, meta = pack_mergetree_batch(docs)
    S = state.tstart.shape[1]
    assert meta["i16_ok"], "small fuzz batch must qualify for int16 export"

    from fluidframework_tpu.ops.mergetree_kernel import export_to_numpy

    ex16 = export_to_numpy(replay_export(None, ops, meta, S=S))
    slots16 = ex16[0] if isinstance(ex16, tuple) else ex16
    assert slots16.dtype == np.int16
    meta32 = dict(meta, i16_ok=False)
    ex32 = export_to_numpy(replay_export(None, ops, meta32, S=S))
    assert ex32.dtype == np.int32
    from fluidframework_tpu.ops.mergetree_kernel import _export_flags

    _i, ob_f, ov_f, i8_f, props_f = _export_flags(meta)
    w16 = widen_export(ex16, meta["doc_base"], ob_rows=ob_f, ov_rows=ov_f,
                       i8=i8_f, n_props=meta["props_K"], props_rows=props_f)
    w32 = widen_export(ex32, None, ob_rows=ob_f, ov_rows=ov_f,
                       n_props=meta["props_K"], props_rows=props_f)
    if i8_f:
        # Bit-equality holds for the slots extraction reads ([0, n) per
        # doc); beyond n the int8 pack truncates dead-slot garbage to 8
        # bits, so the widths legitimately differ there.
        n = w32[:, -1, 0]
        for d in range(w32.shape[0]):
            np.testing.assert_array_equal(
                w16[d, :, :n[d]], w32[d, :, :n[d]], err_msg=f"doc {d}"
            )
    else:
        np.testing.assert_array_equal(w16, w32)
    d16 = [s.digest() for s in summaries_from_export(meta, ex16)]
    d32 = [s.digest() for s in summaries_from_export(meta32, ex32)]
    assert d16 == d32


def test_obliterate_rows_elided_when_chunk_has_none():
    """A chunk with no obliterate ops transfers 4 fewer slot rows; the
    host reinserts sentinels and summaries stay byte-identical.  A chunk
    WITH an obliterate keeps the full layout."""
    import numpy as np

    from fluidframework_tpu.ops.mergetree_kernel import (
        EXPORT_SLOT_FIELDS,
        NON_OB_SLOT_FIELDS,
        pack_mergetree_batch,
        replay_export,
        summaries_from_export,
    )
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def op(seq, contents):
        return SequencedMessage(
            seq=seq, client_id="c0", client_seq=seq, ref_seq=seq - 1,
            min_seq=0, type=MessageType.OP, contents=contents,
        )

    plain = MergeTreeDocInput(
        doc_id="plain",
        ops=[op(1, {"kind": "insert", "pos": 0, "text": "hello"}),
             op(2, {"kind": "remove", "start": 1, "end": 3})],
        final_seq=2, final_msn=0,
    )
    from fluidframework_tpu.ops.mergetree_kernel import export_layout_rows

    state, ops, meta = pack_mergetree_batch([plain])
    assert meta["ob_rows"] is False
    assert meta["ov_rows"] is False  # sequential: rem2 rows elided too
    from fluidframework_tpu.ops.mergetree_kernel import export_to_numpy

    assert meta["i8_ok"], "fixture must qualify for the i8 layout"
    ex = export_to_numpy(replay_export(None, ops, meta, S=state.tstart.shape[1]))
    # i8 layouts return (slot_rows, misc) — the misc row left the buffer
    slots, misc = ex
    assert slots.shape[1] == export_layout_rows(meta)
    assert misc.shape == (1, 4) and misc.dtype == np.int32
    # elisions + byte packing really shrink the buffer vs the full layout
    full_rows = len(EXPORT_SLOT_FIELDS) + meta["props_K"] + 1
    assert slots.shape[1] < full_rows - 5
    [summary] = summaries_from_export(meta, ex)
    replica = SharedString("plain")
    for msg in plain.ops:
        replica.process(msg, local=False)
    assert summary.digest() == replica.summarize().digest()

    obd = MergeTreeDocInput(
        doc_id="ob",
        ops=[op(1, {"kind": "insert", "pos": 0, "text": "hello"}),
             op(2, {"kind": "obliterate", "start": 1, "end": 3})],
        final_seq=2, final_msn=0,
    )
    state2, ops2, meta2 = pack_mergetree_batch([obd])
    assert meta2["ob_rows"] is True
    ex2 = export_to_numpy(
        replay_export(None, ops2, meta2, S=state2.tstart.shape[1])
    )
    slots2 = ex2[0] if isinstance(ex2, tuple) else ex2
    assert slots2.shape[1] == export_layout_rows(meta2)
    [summary2] = summaries_from_export(meta2, ex2)
    replica2 = SharedString("ob")
    for msg in obd.ops:
        replica2.process(msg, local=False)
    assert summary2.digest() == replica2.summarize().digest()


def test_export_i16_disabled_for_wide_values():
    """A chunk whose head sequence exceeds the int16 range must fall back to
    the int32 export and still match the oracle byte-for-byte."""
    import numpy as np

    from fluidframework_tpu.ops.mergetree_kernel import pack_mergetree_batch
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    big = 40_000  # > int16 max
    ops = [
        SequencedMessage(seq=big + i, client_id="c0", client_seq=i + 1,
                         ref_seq=big + i - 1, min_seq=0, type=MessageType.OP,
                         contents={"kind": "insert", "pos": 0, "text": "ab"})
        for i in range(3)
    ]
    doc = MergeTreeDocInput(doc_id="wide", ops=ops, final_seq=big + 3,
                            final_msn=0)
    _state, _ops, meta = pack_mergetree_batch([doc])
    assert not meta["i16_ok"]
    [summary] = replay_mergetree_batch([doc])
    body = json.loads(summary.blob_bytes("body"))
    assert "".join(rec["t"] for rec in body) == "ababab"


@pytest.mark.parametrize("seed", range(6))
def test_mergetree_kernel_obliterate_matches_oracle(seed):
    """Obliterate through the device fold: fuzz logs with obliterate ops
    (concurrent obliterates, obliterate-vs-insert races) replayed by the
    kernel must be byte-identical to the oracle."""
    replicas, factory = run_fuzz(
        StringFuzzSpec(obliterate=True), seed=900 + seed, n_clients=3,
        rounds=14, sync_every=1,
    )
    oracle = replicas[0].summarize()
    [summary] = replay_mergetree_batch([_kernel_inputs_from_fuzz(factory)])
    assert summary.digest() == oracle.digest(), (
        f"seed={seed}: kernel body "
        f"{summary.blob_bytes('body')!r} != oracle "
        f"{oracle.blob_bytes('body')!r}"
    )


def test_mergetree_kernel_obliterate_warm_start():
    """Warm start: a summary with in-window obliterate stamps re-enters the
    kernel as base records and tail inserts still die/survive correctly."""
    replicas, factory = run_fuzz(
        StringFuzzSpec(obliterate=True), seed=950, n_clients=3,
        rounds=10, sync_every=1,
    )
    ops = channel_log(factory, "fuzz")
    mid_seq = ops[len(ops) // 2].seq
    partial = SharedString("fuzz")
    for msg in ops:
        if msg.seq <= mid_seq:
            partial.process(msg, local=False)
    base = partial.summarize()
    import json as _json

    doc = MergeTreeDocInput(
        doc_id="fuzz",
        ops=[m for m in ops if m.seq > mid_seq],
        base_records=_json.loads(base.blob_bytes("body")),
        base_seq=mid_seq, base_msn=partial.tree.min_seq,
        final_seq=factory.sequencer.seq,
        final_msn=factory.sequencer.min_seq,
    )
    [summary] = replay_mergetree_batch([doc])
    assert summary.digest() == replicas[0].summarize().digest()


def test_sequential_tail_over_stamped_base_skips_kills_correctly():
    """The fold's sequential fast path skips the arrival-kill scan even
    when the BASE summary carries obliterate stamps (a stamp seq <=
    base_seq <= every sequential tail ref can never kill).  Pin that
    claim against the oracle: warm doc, in-window base ob stamps, strictly
    sequential tail with inserts landing between stamped slots."""
    import numpy as np

    from fluidframework_tpu.ops.mergetree_kernel import pack_mergetree_batch
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def op(seq, contents):
        return SequencedMessage(
            seq=seq, client_id="c0", client_seq=seq, ref_seq=seq - 1,
            min_seq=0, type=MessageType.OP, contents=contents,
        )

    # Build the base via the oracle: insert then obliterate the middle —
    # the summary retains stamped tombstones in-window.
    base_replica = SharedString("wb")
    for msg in (op(1, {"kind": "insert", "pos": 0, "text": "abcdef"}),
                op(2, {"kind": "obliterate", "start": 1, "end": 5})):
        base_replica.process(msg, local=False)
    base_summary = base_replica.summarize()
    base_records = json.loads(base_summary.blob_bytes("body"))
    assert any("ob" in rec for rec in base_records), \
        "base must carry obliterate stamps for this test to bite"

    tail = [op(3, {"kind": "insert", "pos": 1, "text": "XY"}),
            op(4, {"kind": "remove", "start": 0, "end": 1})]
    doc = MergeTreeDocInput(
        doc_id="wb", ops=tail, base_records=base_records,
        base_seq=2, base_msn=0, final_seq=4, final_msn=0,
    )
    _s, _o, meta = pack_mergetree_batch([doc])
    assert meta["sequential"] and meta["ob_rows"], (
        "fixture must hit the sequential fast path WITH base stamps")

    [summary] = replay_mergetree_batch([doc])
    resumed = SharedString("wb")
    resumed.load(base_summary)
    for msg in tail:
        resumed.process(msg, local=False)
    resumed.advance(4, 0)
    assert summary.digest() == resumed.summarize().digest()


def test_header_fast_format_matches_canonical_json():
    """The hand-formatted header blob must stay byte-equal to
    canonical_json for every value shape the header can carry."""
    from fluidframework_tpu.protocol.summary import canonical_json

    for length, min_seq, seq in [(0, 0, 0), (7, 3, 12), (32766, 1, 983040),
                                 (123456789, 98765, 2**31 - 1)]:
        fast = b'{"length":%d,"minSeq":%d,"seq":%d}' % (length, min_seq, seq)
        assert fast == canonical_json(
            {"seq": seq, "minSeq": min_seq, "length": length})


def test_ob_stamp_author_involvement_in_lagged_view():
    """Fuzz seed 1500041 (minimized): a segment removed by one client but
    carrying ANOTHER client's obliterate stamp must be hidden from views
    in the stamp author's name — the author's optimistic view hid every
    covered slot, so a lagged insert by the author resolves positions
    without it.  The kernel's visibility lacked the stamp-author term and
    placed the insert several chars off."""
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def m(seq, client, ref, contents):
        return SequencedMessage(seq=seq, client_id=client, client_seq=seq,
                                ref_seq=ref, min_seq=0,
                                type=MessageType.OP, contents=contents)

    log = [
        m(1, "c0", 0, {"kind": "insert", "pos": 0, "text": "abcdef"}),
        # c1's remove of [2,4) wins the removal of "cd"...
        m(2, "c1", 1, {"kind": "remove", "start": 2, "end": 4}),
        # ...then c2 obliterates [1,3) of its ref-2 view "abef" — the
        # "cd" tombstone sits at ZERO WIDTH strictly inside the range,
        # so it gets c2's stamp with NO remover bookkeeping (the stamp
        # is the only durable record of c2's coverage).
        m(3, "c2", 2, {"kind": "obliterate", "start": 1, "end": 3}),
        # c2's lagged insert (ref 1, before the removal): in c2's own
        # view "cd" must be HIDDEN (c2 stamped it) even though c1 won
        # the removal and c2 never became its overlap remover — pos 2
        # is the end of "af", not a point inside "cd".
        m(4, "c2", 1, {"kind": "insert", "pos": 2, "text": "XY"}),
    ]
    oracle = SharedString("obinv")
    for msg in log:
        oracle.process(msg, local=False)
    doc = MergeTreeDocInput(doc_id="obinv", ops=log, final_seq=4,
                            final_msn=0)
    [summary] = replay_mergetree_batch([doc])
    assert summary.digest() == oracle.summarize().digest(), (
        "stamp-author involvement: kernel != oracle"
    )
