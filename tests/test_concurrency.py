"""Threaded stress for the serving path's shared state (fluidrace,
ISSUE 4): hammer the two structures PR 3 made concurrent — the
NetworkDriver's pending/response map and the PackCache — from N threads,
and assert no lost updates plus clean shutdown (threads joined, pending
map drained, no daemon leaks).  Budgeted for the `not slow` tier: the
pack leg is stubbed (locking is under test, not the C++ pack) and the
network leg is a few hundred localhost round-trips.
"""

import threading

import numpy as np

from fluidframework_tpu.drivers.network_driver import (
    NetworkDocumentServiceFactory,
)
from fluidframework_tpu.ops import pipeline as pipeline_mod
from fluidframework_tpu.ops.mergetree_kernel import MergeTreeDocInput
from fluidframework_tpu.ops.pipeline import PackCache
from fluidframework_tpu.protocol.messages import MessageType, RawOperation
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.server import OrderingServer

N_THREADS = 8


def _run_threads(worker, n=N_THREADS, timeout=60):
    errors = []

    def guarded(tid):
        try:
            worker(tid)
        except Exception as exc:  # surfaced below, with the assertion
            errors.append(exc)

    threads = [threading.Thread(target=guarded, args=(t,))
               for t in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not [t for t in threads if t.is_alive()], "worker thread hung"
    assert errors == [], errors
    return threads


# --- PackCache ----------------------------------------------------------------


def _stub_pack(chunk):
    state = (np.zeros(64, np.int32),)
    ops = (np.zeros(64, np.int32),)
    return state, ops, {"arena": [], "docs": list(chunk)}


def test_pack_cache_threaded_no_lost_updates(monkeypatch):
    """N threads × (hits + misses + bypasses) over a small key set: every
    call lands in exactly one counter (bumps are atomic under the cache
    lock — a lost update breaks the total), byte accounting matches the
    resident entries exactly, and every returned meta carries the
    caller's own chunk."""
    monkeypatch.setattr(pipeline_mod, "pack_mergetree_batch", _stub_pack)
    cache = PackCache(max_bytes=1 << 20)
    keys = [("epoch", f"doc{i}", 0, "") for i in range(6)]
    per_thread = 60
    bypass_every = 10

    def worker(tid):
        for i in range(per_thread):
            if i % bypass_every == bypass_every - 1:
                chunk = [MergeTreeDocInput(doc_id="nt", ops=[])]  # no token
            else:
                chunk = [MergeTreeDocInput(
                    doc_id="d", ops=[],
                    cache_token=keys[(tid + i) % len(keys)])]
            _state, _ops, meta = cache.pack(chunk)
            assert meta["docs"] == chunk  # never another thread's chunk

    _run_threads(worker)
    stats = cache.stats()
    total = N_THREADS * per_thread
    assert stats["exact_hits"] + stats["misses"] + stats["bypass"] == total
    assert stats["bypass"] == N_THREADS * (per_thread // bypass_every)
    # Misses may exceed the key count (no single-flight here: a herd on a
    # cold key packs concurrently) but every key must have missed once...
    assert stats["misses"] >= len(keys)
    # ...and the LRU must hold exactly the keyed entries, bytes exact.
    assert stats["entries"] == len(cache._entries)
    assert set(cache._entries) == {(k,) for k in keys}
    assert stats["bytes"] == sum(
        e.nbytes for e in cache._entries.values())
    assert stats["evictions"] == 0


# --- NetworkDriver pending map ------------------------------------------------


def test_network_pending_map_threaded_and_clean_shutdown():
    """N client threads share ONE socket: concurrent requests must each
    get their own response (the reader routes by id through the pending
    map), sequencing must lose nothing, and close() must wind down the
    reader + dispatcher threads (daemon threads still must exit — a leak
    is a stuck thread holding the dead socket)."""
    srv = OrderingServer(port=0)
    srv.start_in_thread()
    factory = NetworkDocumentServiceFactory(port=srv.port)
    seeded = ContainerRuntime()
    seeded.create_datastore("ds").create_channel("sequence-tpu", "t")
    svc = factory.create_document("stress", seeded.summarize())
    conn = svc.connection()
    rpc = factory._rpc
    per_thread = 25
    seqs = [[] for _ in range(N_THREADS)]

    def worker(tid):
        client = f"c{tid}"
        conn.connect(client)
        # First submit must reference a view inside the collaboration
        # window: concurrent earlier submitters may already have advanced
        # the MSN past 0 (connect floors this client at the seq it joined
        # on, so the post-connect head is always a valid view).
        ref_seq = conn.head_seq
        for i in range(per_thread):
            assert rpc.request("ping", {}) == "pong"
            msg = conn.submit(RawOperation(
                client_id=client, client_seq=i + 1, ref_seq=ref_seq,
                type=MessageType.OP, contents={"tid": tid, "i": i}))
            assert msg is not None
            seqs[tid].append(msg.seq)
            ref_seq = msg.seq  # keep the view inside the MSN window
        conn.disconnect(client)

    _run_threads(worker)
    all_seqs = [s for per in seqs for s in per]
    # No lost updates: every submit was sequenced exactly once, and each
    # thread saw ITS OWN acks in submission order (responses routed to
    # the right waiter, never cross-delivered).
    assert len(set(all_seqs)) == N_THREADS * per_thread
    for per in seqs:
        assert per == sorted(per)
    assert conn.head_seq >= max(all_seqs)
    with rpc._pending_lock:
        assert rpc._pending == {}, "pending map must drain to empty"
    # Clean shutdown: both driver threads exit once the socket closes.
    factory.close()
    rpc._reader.join(timeout=10)
    rpc._dispatcher.join(timeout=10)
    assert not rpc._reader.is_alive(), "reader thread leaked"
    assert not rpc._dispatcher.is_alive(), "dispatcher thread leaked"
