"""Threaded stress for the serving path's shared state (fluidrace,
ISSUE 4): hammer the two structures PR 3 made concurrent — the
NetworkDriver's pending/response map and the PackCache — from N threads,
and assert no lost updates plus clean shutdown (threads joined, pending
map drained, no daemon leaks).  Budgeted for the `not slow` tier: the
pack leg is stubbed (locking is under test, not the C++ pack) and the
network leg is a few hundred localhost round-trips.

ISSUE 7 adds the broadcaster backpressure stress: a subscriber that
stops reading must be DEMOTED (catch-up-from-oplog) without stalling
the shard or the other subscribers.
"""

import json
import socket
import threading
import time

import numpy as np

from fluidframework_tpu.drivers.network_driver import (
    NetworkDocumentServiceFactory,
)
from fluidframework_tpu.ops import pipeline as pipeline_mod
from fluidframework_tpu.ops.mergetree_kernel import MergeTreeDocInput
from fluidframework_tpu.ops.pipeline import PackCache
from fluidframework_tpu.protocol.messages import MessageType, RawOperation
from fluidframework_tpu.protocol.wire import LEN, frame_bytes
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.server import OrderingServer

N_THREADS = 8


def _run_threads(worker, n=N_THREADS, timeout=60):
    errors = []

    def guarded(tid):
        try:
            worker(tid)
        except Exception as exc:  # surfaced below, with the assertion
            errors.append(exc)

    threads = [threading.Thread(target=guarded, args=(t,))
               for t in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not [t for t in threads if t.is_alive()], "worker thread hung"
    assert errors == [], errors
    return threads


# --- PackCache ----------------------------------------------------------------


def _stub_pack(chunk):
    state = (np.zeros(64, np.int32),)
    ops = (np.zeros(64, np.int32),)
    return state, ops, {"arena": [], "docs": list(chunk)}


def test_pack_cache_threaded_no_lost_updates(monkeypatch):
    """N threads × (hits + misses + bypasses) over a small key set: every
    call lands in exactly one counter (bumps are atomic under the cache
    lock — a lost update breaks the total), byte accounting matches the
    resident entries exactly, and every returned meta carries the
    caller's own chunk."""
    monkeypatch.setattr(pipeline_mod, "pack_mergetree_batch", _stub_pack)
    cache = PackCache(max_bytes=1 << 20)
    keys = [("epoch", f"doc{i}", 0, "") for i in range(6)]
    per_thread = 60
    bypass_every = 10

    def worker(tid):
        for i in range(per_thread):
            if i % bypass_every == bypass_every - 1:
                chunk = [MergeTreeDocInput(doc_id="nt", ops=[])]  # no token
            else:
                chunk = [MergeTreeDocInput(
                    doc_id="d", ops=[],
                    cache_token=keys[(tid + i) % len(keys)])]
            _state, _ops, meta = cache.pack(chunk)
            assert meta["docs"] == chunk  # never another thread's chunk

    _run_threads(worker)
    stats = cache.stats()
    total = N_THREADS * per_thread
    assert stats["exact_hits"] + stats["misses"] + stats["bypass"] == total
    assert stats["bypass"] == N_THREADS * (per_thread // bypass_every)
    # Misses may exceed the key count (no single-flight here: a herd on a
    # cold key packs concurrently) but every key must have missed once...
    assert stats["misses"] >= len(keys)
    # ...and the LRU must hold exactly the keyed entries, bytes exact.
    assert stats["entries"] == len(cache._entries)
    assert set(cache._entries) == {(k,) for k in keys}
    assert stats["bytes"] == sum(
        e.nbytes for e in cache._entries.values())
    assert stats["evictions"] == 0


# --- NetworkDriver pending map ------------------------------------------------


def test_network_pending_map_threaded_and_clean_shutdown():
    """N client threads share ONE socket: concurrent requests must each
    get their own response (the reader routes by id through the pending
    map), sequencing must lose nothing, and close() must wind down the
    reader + dispatcher threads (daemon threads still must exit — a leak
    is a stuck thread holding the dead socket)."""
    srv = OrderingServer(port=0)
    srv.start_in_thread()
    factory = NetworkDocumentServiceFactory(port=srv.port)
    seeded = ContainerRuntime()
    seeded.create_datastore("ds").create_channel("sequence-tpu", "t")
    svc = factory.create_document("stress", seeded.summarize())
    conn = svc.connection()
    rpc = factory._rpc
    per_thread = 25
    seqs = [[] for _ in range(N_THREADS)]

    def worker(tid):
        client = f"c{tid}"
        conn.connect(client)
        # First submit must reference a view inside the collaboration
        # window: concurrent earlier submitters may already have advanced
        # the MSN past 0 (connect floors this client at the seq it joined
        # on, so the post-connect head is always a valid view).
        ref_seq = conn.head_seq
        for i in range(per_thread):
            assert rpc.request("ping", {}) == "pong"
            msg = conn.submit(RawOperation(
                client_id=client, client_seq=i + 1, ref_seq=ref_seq,
                type=MessageType.OP, contents={"tid": tid, "i": i}))
            assert msg is not None
            seqs[tid].append(msg.seq)
            ref_seq = msg.seq  # keep the view inside the MSN window
        conn.disconnect(client)

    _run_threads(worker)
    all_seqs = [s for per in seqs for s in per]
    # No lost updates: every submit was sequenced exactly once, and each
    # thread saw ITS OWN acks in submission order (responses routed to
    # the right waiter, never cross-delivered).
    assert len(set(all_seqs)) == N_THREADS * per_thread
    for per in seqs:
        assert per == sorted(per)
    assert conn.head_seq >= max(all_seqs)
    with rpc._pending_lock:
        assert rpc._pending == {}, "pending map must drain to empty"
    # Clean shutdown: both driver threads exit once the socket closes.
    factory.close()
    rpc._reader.join(timeout=10)
    rpc._dispatcher.join(timeout=10)
    assert not rpc._reader.is_alive(), "reader thread leaked"
    assert not rpc._dispatcher.is_alive(), "dispatcher thread leaked"


# --- broadcaster backpressure: laggard demotion under load --------------------


def _raw_read_frame(sock_file):
    header = sock_file.read(LEN.size)
    if len(header) != LEN.size:
        return None
    (length,) = LEN.unpack(header)
    payload = sock_file.read(length)
    return json.loads(payload)


def test_broadcast_laggard_demoted_without_stalling(tmp_path):
    """One subscriber stops reading while others stay hot: the server
    must demote the laggard at its broadcast buffer budget (never stall
    the shard, never buffer unboundedly, never punish the healthy
    subscribers), deliver every op to the fast clients, and hand the
    laggard a 'demoted' event it can act on when it wakes up."""
    # Budget sized so a READING subscriber never trips it even under
    # full-suite GC-pause jitter (~90 frames of headroom; the writer is
    # RPC-paced and the fast reader drains localhost promptly) while the
    # sleeping laggard — whose backlog only ever grows — reliably does
    # within the op cap below.
    srv = OrderingServer(port=0, broadcast_high_water=1_500_000)
    srv.start_in_thread()
    seed_factory = NetworkDocumentServiceFactory(port=srv.port)
    fast_factory = NetworkDocumentServiceFactory(port=srv.port)
    laggard_sock = None
    try:
        seeded = ContainerRuntime()
        seeded.create_datastore("ds").create_channel("sequence-tpu", "t")
        svc = seed_factory.create_document("lag", seeded.summarize())
        conn = svc.connection()
        conn.connect("writer")

        # Laggard: a raw-protocol subscriber that reads its subscribe
        # response and then goes to sleep with the firehose on.
        laggard_sock = socket.create_connection(("127.0.0.1", srv.port),
                                                timeout=10)
        laggard_file = laggard_sock.makefile("rb")
        laggard_sock.sendall(frame_bytes(
            {"v": 1, "id": 1, "method": "subscribe_doc",
             "params": {"doc": "lag"}}))
        assert _raw_read_frame(laggard_file)["ok"]

        # Healthy subscriber on its own socket.
        fast_conn = fast_factory.resolve("lag").connection()
        fast_seqs = []
        fast_conn.subscribe(lambda m: fast_seqs.append(m.seq))
        fast_conn.connect("fastreader")

        # Firehose: chunky ops until the server demotes the laggard (or
        # a generous cap trips the assertion).
        payload = "x" * 16384
        submitted = []
        ref = conn.head_seq
        for i in range(400):
            msg = conn.submit(RawOperation(
                client_id="writer", client_seq=i + 1, ref_seq=ref,
                type=MessageType.OP, contents={"blob": payload}))
            ref = msg.seq
            submitted.append(msg.seq)
            if srv.broadcaster.stats()["demotions"] >= 1:
                break
        stats = srv.broadcaster.stats()
        assert stats["demotions"] >= 1, \
            f"laggard never demoted after {len(submitted)} chunky ops"

        # The shard never stalled: post-demotion traffic flows...
        for i in range(10):
            msg = conn.submit(RawOperation(
                client_id="writer", client_seq=len(submitted) + i + 1,
                ref_seq=ref, type=MessageType.OP, contents={"i": i}))
            ref = msg.seq
            submitted.append(msg.seq)
        # ...and the healthy subscriber receives EVERY op.  (fast_seqs
        # also carries JOIN/LEAVE broadcasts, so compare by CONTENT, not
        # length.)
        deadline = time.time() + 20
        while not set(submitted) <= set(fast_seqs) \
                and time.time() < deadline:
            time.sleep(0.02)
        assert set(submitted) <= set(fast_seqs), (
            f"fast subscriber missing "
            f"{sorted(set(submitted) - set(fast_seqs))[:5]}")
        # ...with no collateral demotion: one laggard cost ONLY itself.
        assert fast_conn.demotions_seen == 0

        # The woken laggard drains its backlog and finds the demotion
        # notice — its cue to backfill from the op log and re-subscribe.
        events = []
        deadline = time.time() + 20
        laggard_sock.settimeout(20)
        while time.time() < deadline:
            frame = _raw_read_frame(laggard_file)
            assert frame is not None, "server dropped the laggard's socket"
            if frame.get("event") == "demoted":
                events.append(frame)
                break
        assert events and events[0]["doc"] == "lag"
        assert events[0]["head"] > 0
        # re-subscribe works: the demotion was a state reset, not a ban
        laggard_sock.sendall(frame_bytes(
            {"v": 1, "id": 2, "method": "subscribe_doc",
             "params": {"doc": "lag"}}))
        while True:
            frame = _raw_read_frame(laggard_file)
            assert frame is not None
            if frame.get("re") == 2:
                assert frame["ok"]
                break
    finally:
        if laggard_sock is not None:
            laggard_sock.close()
        fast_factory.close()
        seed_factory.close()


def test_demoted_client_backfills_even_if_doc_goes_quiet():
    """The demotion contract's hard case: the burst that demoted the
    client was the document's LAST activity.  Gap repair only fires on a
    later live message, so the driver's demoted handler must kick the
    backfill itself (re-subscribe + deliver the head op) or the dropped
    span would be missing forever."""
    srv = OrderingServer(port=0)
    srv.start_in_thread()
    factory = NetworkDocumentServiceFactory(port=srv.port)
    try:
        seeded = ContainerRuntime()
        seeded.create_datastore("ds").create_channel("sequence-tpu", "t")
        svc = factory.create_document("quiet", seeded.summarize())
        conn = svc.connection()
        got = []
        conn.subscribe(lambda m: got.append(m.seq))
        conn.connect("w")
        ref = conn.head_seq
        ref = conn.submit(RawOperation(
            client_id="w", client_seq=1, ref_seq=ref,
            type=MessageType.OP, contents={"i": 0})).seq
        deadline = time.time() + 10
        while ref not in got and time.time() < deadline:
            time.sleep(0.02)
        assert ref in got
        # Force the NEXT broadcast to demote this session, then restore.
        srv.broadcast_high_water = 0
        last = conn.submit(RawOperation(
            client_id="w", client_seq=2, ref_seq=ref,
            type=MessageType.OP, contents={"i": 1})).seq
        srv.broadcast_high_water = 8 << 20
        # No further traffic: the kicked backfill alone must deliver the
        # dropped head op.
        deadline = time.time() + 20
        while last not in got and time.time() < deadline:
            time.sleep(0.02)
        assert last in got, "demoted client never backfilled the quiet doc"
        assert conn.demotions_seen >= 1
        # ...and the restored tap is live for future traffic.
        nxt = conn.submit(RawOperation(
            client_id="w", client_seq=3, ref_seq=last,
            type=MessageType.OP, contents={"i": 2})).seq
        deadline = time.time() + 10
        while nxt not in got and time.time() < deadline:
            time.sleep(0.02)
        assert nxt in got
    finally:
        factory.close()
