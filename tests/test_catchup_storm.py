"""Catch-up storms (ISSUE 15): adaptive admission, degraded-mode
serving, the catchup fault seams, per-client relay flow control, and
the storm scenario family that drives herd joins through the REAL
catchup path.

The directed pins here complement the scenario-level matrices in
tests/test_scenarios.py (catchup-storm rides the same smoke / replay /
parity / 10⁵ grids as every family):

- AdmissionController: load-derived retry_after pacing, virtual-time
  lease occupancy, measured-cost EMA — all off an injected clock.
- The warm priority lane bypasses the fold semaphore; N concurrent
  catch-ups of one document cost ONE admission slot (join ≠ fold).
- Shed clients honor the load-derived retry_after through RetryPolicy
  under VirtualClock and still converge.
- Degraded-mode serving answers the stored summary at an older
  ref_seq; loading from it + the durable tail is byte-identical to a
  fresh fold (convergence is never weakened).  Gated by
  Catchup.DegradedServe.
- catchup.fail / catchup.slow fire deterministically and take the real
  recovery paths.
- The front door's broadcast relay is per-client budget-bounded: a
  laggard saturates its own queue and is demoted (existing contract);
  control frames bypass the budget.
- slow tier: the TCP front door at 10⁴ real connections (PR 10's
  "unexplored" corner) with per-connection memory bounds.
"""

import dataclasses
import random
import socket
import struct
import threading
import time

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.protocol.messages import NackError
from fluidframework_tpu.service.catchup import CatchupService
from fluidframework_tpu.service.orderer import LocalOrderingService
from fluidframework_tpu.service.retry import RetryPolicy
from fluidframework_tpu.service.server import (AdmissionController,
                                               OrderingServer)
from fluidframework_tpu.testing.faults import (FaultInjector, FaultPlan,
                                               FaultPoint)
from fluidframework_tpu.testing.load import VirtualClock
from fluidframework_tpu.utils.telemetry import (ConfigProvider,
                                                LockedCounterSet,
                                                MonitoringContext)


class _Session:
    tenant = None


def _mc(**settings):
    return MonitoringContext(config=ConfigProvider(settings))


def _service_with_doc(doc="doc", sets=3, summarize_at_head=False):
    """A LocalOrderingService holding one map-channel document with an
    attach summary and ``sets`` ops of durable tail; optionally a fresh
    summary AT the head (the fully-warm shape)."""
    service = LocalOrderingService()
    loader = Loader(LocalDocumentServiceFactory(service))

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("map-tpu", "kv")

    client = loader.create(doc, "alice", build)
    kv = client.runtime.get_datastore("ds").get_channel("kv")
    for k in range(sets):
        kv.set(f"k{k}", k)
    client.drain()
    client.close()
    if summarize_at_head:
        ro = loader.resolve(doc)
        service.storage.upload(doc, ro.runtime.summarize(),
                               ro.runtime.ref_seq)
        ro.close()
    return service, loader


def _append_op(service, doc="doc", client="w", key="late", value=9):
    """Stamp one more durable map-set (JOIN + OP) past whatever summary
    exists — the 'tail grew since the stored summary' shape."""
    from fluidframework_tpu.protocol.messages import (MessageType,
                                                      RawOperation)
    from fluidframework_tpu.runtime.op_pipeline import BATCH_WIRE_VERSION

    ep = service.endpoint(doc)
    ep.connect(client)
    head = service.oplog.head(doc)
    ep.submit(RawOperation(
        client_id=client, client_seq=1, ref_seq=head,
        type=MessageType.OP,
        contents={"type": "groupedBatch", "v": BATCH_WIRE_VERSION,
                  "ops": [{"clientSeq": 1, "refSeq": head, "ds": "ds",
                           "channel": "kv",
                           "contents": {"kind": "set", "key": key,
                                        "value": value}}]}))
    ep.disconnect(client)


# --- AdmissionController -------------------------------------------------------


def test_admission_retry_after_scales_with_backlog_and_clamps():
    clock = VirtualClock()
    ctl = AdmissionController(2, clock=clock, retry_floor=0.1,
                              retry_cap=3.0, cost_init=0.5)
    verdict, t1 = ctl.admit()
    assert verdict == "admit"
    verdict, _t2 = ctl.admit()
    assert verdict == "admit"
    # full: consecutive overflows deepen the backlog estimate and pace
    # retries further out — monotonic, floor/cap-clamped
    holds = []
    for _ in range(8):
        verdict, retry_after = ctl.admit()
        assert verdict in ("shed", "degrade")
        holds.append(retry_after)
    assert holds == sorted(holds)
    assert holds[0] >= 0.1
    assert holds[-1] <= 3.0
    assert holds[-1] > holds[0]
    # a freed slot resets the streak
    ctl.release(t1)
    verdict, _tok = ctl.admit()
    assert verdict == "admit"
    assert ctl.snapshot()["shed_streak"] == 0


def test_admission_lease_hold_occupies_virtual_time():
    clock = VirtualClock()
    ctl = AdmissionController(1, clock=clock, cost_init=0.1)
    _v, token = ctl.admit()
    ctl.release(token, hold=2.0)  # modeled fold duration: 2s of clock
    assert ctl.admit()[0] in ("shed", "degrade")  # still occupied
    clock.sleep(2.5)
    verdict, _tok = ctl.admit()  # lease expired on the clock
    assert verdict == "admit"


def test_admission_cost_ema_tracks_measured_cost():
    clock = VirtualClock()
    ctl = AdmissionController(1, clock=clock, cost_init=0.2)
    _v, token = ctl.admit()
    clock.sleep(4.0)  # the fold "ran" 4 virtual seconds
    ctl.release(token)
    assert ctl.snapshot()["cost_ema"] > 1.0  # 0.5*0.2 + 0.5*~4


# --- the warm priority lane ----------------------------------------------------


def test_warm_requests_bypass_fold_admission():
    service, _loader = _service_with_doc(summarize_at_head=True)
    server = OrderingServer(service, catchup_max_inflight=1,
                            clock=VirtualClock())
    # saturate the fold lane: the one slot is leased out
    verdict, _token = server.admission_control.admit()
    assert verdict == "admit"
    out = server._dispatch(_Session(), "catchup", {"docs": ["doc"]})
    assert out["lane"] == "warm"
    assert "doc" in out["docs"]
    snap = server.admission.snapshot()
    assert snap["catchup.warm"] == 1
    assert snap["catchup.requests"] == 0  # never entered the fold lane
    assert snap["catchup.shed"] == 0


def test_single_flight_herd_costs_one_admission_slot(monkeypatch):
    """THE satellite pin: N concurrent catch_up calls on one document
    cost ONE admission slot — followers ride the single-flight join in
    the warm lane (a join is not a fold)."""
    service, _loader = _service_with_doc(sets=4)
    server = OrderingServer(service, catchup_max_inflight=4)
    entered = threading.Event()
    release = threading.Event()
    real_cpu = CatchupService._cpu_fold

    def slow_cpu(self, work):
        entered.set()
        assert release.wait(timeout=30)
        return real_cpu(self, work)

    monkeypatch.setattr(CatchupService, "_cpu_fold", slow_cpu)
    monkeypatch.setattr(CatchupService, "_device_plan",
                        lambda self, work: None)
    results = []
    errors = []

    def call():
        try:
            results.append(
                server._dispatch(_Session(), "catchup", {"docs": ["doc"]}))
        except BaseException as exc:  # surfaced via the errors list
            errors.append(exc)

    leader = threading.Thread(target=call)
    leader.start()
    assert entered.wait(timeout=30)  # the flight is registered
    followers = [threading.Thread(target=call) for _ in range(3)]
    for f in followers:
        f.start()
    time.sleep(0.2)  # followers reach the single-flight join
    release.set()
    leader.join(timeout=30)
    for f in followers:
        f.join(timeout=30)
    assert not errors
    assert len(results) == 4
    handles = {tuple(r["docs"]["doc"]) for r in results}
    assert len(handles) == 1  # everyone served the leader's one fold
    snap = server.admission.snapshot()
    assert snap["catchup.admitted"] == 1
    assert snap["catchup.warm"] == 3
    assert snap["catchup.shed"] == 0


# --- shed pacing × RetryPolicy -------------------------------------------------


def test_shed_retry_after_honored_by_retry_policy_under_virtual_clock():
    """A shed client waits the server's load-derived retry_after (via
    RetryPolicy's nack hold) on the SAME virtual clock the admission
    controller measures with — once the blocking lease expires, the
    retry admits and the fold serves."""
    clock = VirtualClock()
    service, _loader = _service_with_doc(sets=3)
    server = OrderingServer(service, catchup_max_inflight=1, clock=clock)
    _v, token = server.admission_control.admit()
    server.admission_control.release(token, hold=1.5)  # occupied 1.5s
    counters = LockedCounterSet()
    out = RetryPolicy(max_attempts=6, budget=60.0).run(
        lambda: server._dispatch(_Session(), "catchup", {"docs": ["doc"]}),
        operation="storm catchup",
        sleep=clock.sleep,
        rng=random.Random(0),
        counters=counters,
    )
    assert out["lane"] == "fold"
    snap = server.admission.snapshot()
    assert snap["catchup.shed"] >= 1
    assert counters.get("retry.nack_holds") >= 1
    assert counters.get("retry.retries") >= 1


# --- degraded-mode serving -----------------------------------------------------


def test_degraded_serving_after_sustained_overload_converges():
    """Sustained overload serves the STORED summary at an older
    ref_seq; a client loading that summary plus the durable tail lands
    byte-identical to the fresh fold — freshness weakened, convergence
    untouched."""
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.runtime.registry import default_registry

    service, loader = _service_with_doc(sets=2, summarize_at_head=True)
    _append_op(service)  # grow the tail PAST the stored summary
    server = OrderingServer(
        service, catchup_max_inflight=1, clock=VirtualClock(),
        mc=_mc(**{"Catchup.DegradeAfter": 0}))
    _v, _token = server.admission_control.admit()  # saturate; never freed
    out = server._dispatch(_Session(), "catchup", {"docs": ["doc"]})
    assert out["lane"] == "degraded"
    assert out["degraded"] == ["doc"]
    handle, ref_seq = out["docs"]["doc"]
    assert ref_seq < service.oplog.head("doc")  # genuinely stale
    snap = server.admission.snapshot()
    assert snap["catchup.degraded"] == 1
    assert snap["catchup.degraded_docs"] == 1
    # convergence: stored summary + durable tail == full fresh state
    rt = ContainerRuntime(default_registry())
    rt.load(service.storage.read(handle))
    for msg in service.oplog.get("doc", from_seq=ref_seq):
        rt.process(msg)
    check = loader.resolve("doc")
    assert rt.summarize().digest() == check.runtime.summarize().digest()
    check.close()


def test_degraded_serve_gate_off_sheds_instead():
    service, _loader = _service_with_doc(sets=2, summarize_at_head=True)
    _append_op(service)
    server = OrderingServer(
        service, catchup_max_inflight=1, clock=VirtualClock(),
        mc=_mc(**{"Catchup.DegradeAfter": 0,
                  "Catchup.DegradedServe": "off"}))
    server.admission_control.admit()
    with pytest.raises(NackError) as exc_info:
        server._dispatch(_Session(), "catchup", {"docs": ["doc"]})
    assert exc_info.value.code == "overloaded"
    snap = server.admission.snapshot()
    assert snap["catchup.degraded"] == 0
    assert snap["catchup.shed"] == 1


def test_drain_retry_after_is_gate_configurable():
    server = OrderingServer(LocalOrderingService(),
                            mc=_mc(**{"Server.DrainRetryAfter": 2.5}))
    server.draining = True
    assert server._dispatch(_Session(), "ping", {}) == "pong"
    with pytest.raises(NackError) as exc_info:
        server._dispatch(_Session(), "has_document", {"doc": "d"})
    assert exc_info.value.code == "shuttingDown"
    assert exc_info.value.retry_after == 2.5


# --- the catchup fault seams ---------------------------------------------------


def test_catchup_fail_releases_slot_and_caller_retries():
    service, _loader = _service_with_doc(sets=3)
    injector = FaultInjector(FaultPlan(seed=1, points=(
        FaultPoint("catchup.fail", "fail", at=1),
    )))
    server = OrderingServer(service, catchup_max_inflight=1,
                            clock=VirtualClock(), faults=injector)
    with pytest.raises(OSError):
        server._dispatch(_Session(), "catchup", {"docs": ["doc"]})
    # the admission lease was released by the finally, no flight is
    # stranded, and the immediate retry serves
    assert server.admission_control.snapshot()["inflight"] == 0
    assert server._catchup.cache._flights == {}
    out = server._dispatch(_Session(), "catchup", {"docs": ["doc"]})
    assert out["lane"] == "fold"
    assert injector.snapshot() == {"catchup.fail:fail": 1}
    assert injector.unfired() == []


def test_catchup_slow_raises_measured_cost_and_pacing():
    clock = VirtualClock()
    service, _loader = _service_with_doc(sets=3)
    injector = FaultInjector(FaultPlan(seed=1, points=(
        FaultPoint("catchup.slow", "delay", at=1, arg=3.0),
    )))
    server = OrderingServer(service, catchup_max_inflight=1, clock=clock,
                            faults=injector)
    out = server._dispatch(_Session(), "catchup", {"docs": ["doc"]})
    assert out["lane"] == "fold"
    assert injector.snapshot() == {"catchup.slow:delay": 1}
    # the injected delay registered in the measured-cost EMA...
    assert server.admission_control.snapshot()["cost_ema"] > 1.0
    # ...and the next overload's pacing reflects the slower tier (grow
    # the tail so the request needs a fold, then saturate the one slot)
    _append_op(service)
    server.admission_control.admit()
    with pytest.raises(NackError) as exc_info:
        server._dispatch(_Session(), "catchup", {"docs": ["doc"]})
    assert exc_info.value.retry_after > 1.0


def test_catchup_sites_validate_and_chaos_harness_rejects_them(tmp_path):
    FaultPoint("catchup.slow", "delay", at=1, arg=0.5).validate()
    FaultPoint("catchup.fail", "fail").validate()
    with pytest.raises(ValueError):
        FaultPoint("catchup.slow", "fail").validate()
    from fluidframework_tpu.testing.load import (ChaosLoadSpec,
                                                 run_chaos_load)
    spec = ChaosLoadSpec(
        seed=1, shards=2, docs=2, clients_per_doc=1, steps=10,
        plan=FaultPlan(seed=1, points=(
            FaultPoint("catchup.fail", "fail"),
        )))
    with pytest.raises(ValueError, match="catchup"):
        run_chaos_load(spec)


# --- the storm scenario (10³ tier-1 smoke of the acceptance run) ---------------


def test_storm_smoke_converges_balances_and_replays():
    """The 10⁴ acceptance run at smoke scale: herd joins through the
    REAL catchup path survive with the admission counters balancing
    exactly (admitted + shed + degraded = requests), every shed and
    degraded client converges byte-identical to the never-shed oracle,
    the catchup fault seams fire, and the whole run — counters
    included — replays bit-identically."""
    from fluidframework_tpu.testing.scenarios import (build_scenario,
                                                      oracle_spec,
                                                      run_swarm)

    spec = build_scenario("catchup-storm", seed=3, clients=800, docs=8,
                          shards=4)
    result = run_swarm(spec)
    storm = result.storm
    assert storm["served"] == storm["requests"] > 0
    assert storm["shed"] > 0 or storm["degraded"] > 0, \
        "the storm must actually overload the fold lane"
    assert storm["warm"] > 0, "the warm priority lane must serve"
    admission = storm["admission"]
    assert admission["catchup.requests"] == (
        admission["catchup.admitted"] + admission["catchup.shed"]
        + admission["catchup.degraded"])
    assert result.fault_counts.get("catchup.slow:delay", 0) >= 1
    assert result.fault_counts.get("catchup.fail:fail", 0) >= 1
    assert storm["latency_p99_ticks"] <= 64.0
    # never-shed oracle: byte-identical state
    oracle = run_swarm(oracle_spec(spec, result))
    assert oracle.storm["shed"] == 0 and oracle.storm["degraded"] == 0
    assert result.sampled_digests == oracle.sampled_digests
    assert result.per_doc_head == oracle.per_doc_head
    # replay bit-identity, storm counters included
    assert run_swarm(spec).identity() == result.identity()


# --- front-door relay flow control ---------------------------------------------


class _FakeSock:
    """A socket double for PumpConnection: accepts every byte."""

    def __init__(self):
        self.sent = []

    def getpeername(self):
        return ("test", 0)

    def send(self, view):
        self.sent.append(bytes(view))
        return len(view)

    def shutdown(self, how):
        pass

    def close(self):
        pass


class _FakePump:
    """Pump double: flushing is EXPLICIT (`drain(conn)`), which is the
    event-loop model's laggard — a connection whose kernel buffer has
    not accepted its bytes yet is simply one the loop has not drained."""

    def mark_dirty(self, conn):
        pass

    def drop(self, conn):
        conn.close()


def _frontdoor_shell(tmp_path, relay_budget):
    """A FrontDoor OBJECT (never started — no processes, no sockets):
    the relay fan-out and demotion paths are plain methods on it."""
    from fluidframework_tpu.service.frontdoor import FrontDoor

    return FrontDoor(str(tmp_path / "fd"), n_shards=1, spawn="thread",
                     relay_budget=relay_budget)


def test_relay_budget_demotes_laggard_without_collateral(tmp_path):
    from fluidframework_tpu.service.framepump import PumpConnection

    fd = _frontdoor_shell(tmp_path, relay_budget=300)
    pump = _FakePump()
    # the healthy reader gets a roomy budget (a burst may momentarily
    # outpace the loop's flush passes); the stalled one a tight 300 B
    fast = PumpConnection(_FakeSock(), pump, relay_budget=1 << 20)
    slow = PumpConnection(_FakeSock(), pump, relay_budget=300)
    for s in (fast, slow):
        s.subscribed.add("doc")
    fd._subs["doc"] = [fast, slow]
    frame = {"v": 1, "event": "op", "doc": "doc", "msg": {"pad": "x" * 80}}
    for _ in range(12):
        fd._relay_event(frame)  # slow is never flushed: a stopped reader
    # the laggard was demoted from this doc's fan-out, once
    assert fd.counters.get("fd.relay_demotions") == 1
    assert slow not in fd._subs["doc"]
    assert fast in fd._subs["doc"]
    # its queued bytes stayed bounded: budget + the priority demote frame
    assert slow.pending_bytes() < 300 + 200
    # the fast client sees every frame once the loop flushes it,
    # unstalled by the laggard
    assert fast.flush()
    assert len(fast.sock.sent) == 12
    # the laggard's reader returns: its bounded queue drains and the
    # DEMOTED notice arrives (first — it jumped the queue)
    assert slow.flush()
    assert slow.relay_pending() == 0 and slow.pending_bytes() == 0
    assert b'"demoted"' in slow.sock.sent[0]
    fast.close()
    slow.close()


def test_relay_priority_frames_bypass_budget():
    from fluidframework_tpu.service.framepump import PumpConnection

    conn = PumpConnection(_FakeSock(), _FakePump(), relay_budget=64)
    assert conn.relay(b"x" * 60)  # first frame: queued, charged
    assert not conn.relay(b"y" * 60)  # budget exhausted, un-drained
    conn.relay_priority(b"z" * 60)  # control frame still enqueues
    assert conn.pending_bytes() > 64
    assert conn.relay_pending() == 60  # only relay() charges the budget
    assert conn.flush()
    assert conn.relay_pending() == 0 and conn.pending_bytes() == 0
    # priority frame jumped the queue: z drained before x
    assert conn.sock.sent == [b"z" * 60, b"x" * 60]
    conn.close()


def test_frontdoor_stats_roll_up_admission_and_relay(tmp_path):
    """Satellite pin: the supervisor stats() view aggregates every
    shard's admission counters (storm/degrade included) and reports the
    relay flow-control health — not just per-shard snapshots."""
    from fluidframework_tpu.service.frontdoor import FrontDoor

    fd = FrontDoor(str(tmp_path / "fd"), n_shards=2,
                   spawn="thread").start()
    try:
        stats = fd.stats()
        for key in ("catchup.requests", "catchup.admitted",
                    "catchup.shed", "catchup.degraded", "catchup.warm"):
            assert key in stats["admission"], key
        assert stats["relay"]["sessions"] == 0
        assert stats["relay"]["budget_per_session"] == 4 << 20
        assert "fd.relay_demotions" in stats["counters"]
    finally:
        fd.close()


# --- the TCP front door at 10⁴ real connections (slow tier) --------------------


_LEN = struct.Struct(">I")


def _ping(sock):
    import json as _json

    payload = _json.dumps(
        {"v": 1, "id": 1, "method": "ping", "params": {}}).encode()
    sock.sendall(_LEN.pack(len(payload)) + payload)
    header = b""
    while len(header) < 4:
        header += sock.recv(4 - len(header))
    (length,) = _LEN.unpack(header)
    body = b""
    while len(body) < length:
        body += sock.recv(length - len(body))
    return _json.loads(body)


def _proc_rss_kb(pid: int) -> int:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


@pytest.mark.slow
def test_tcp_front_door_10k_connections():
    """PR 10 left the TCP front door 'unexplored' at 10⁴+ real
    connections.  Pin accept/connect behavior (every connection
    accepted and answering) and the per-connection SERVER memory bound
    — the asyncio single-server shape, run as its own process exactly
    like a deployment (and so each side's fd budget holds one end)."""
    import resource
    import subprocess
    import sys as _sys

    conns = 10_000
    need = conns + 2048
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if hard < need:
        pytest.skip(f"fd hard limit {hard} < {need}")
    if soft < need:
        resource.setrlimit(resource.RLIMIT_NOFILE, (need, hard))
    proc = subprocess.Popen(
        [_sys.executable, "-m", "fluidframework_tpu.service.server",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    socks = []
    try:
        line = proc.stdout.readline()
        assert "listening on" in line, line
        port = int(line.rsplit(":", 1)[1])
        rss_before = _proc_rss_kb(proc.pid)
        for _ in range(conns):
            socks.append(socket.create_connection(("127.0.0.1", port),
                                                  timeout=30))
        # every 100th connection answers (sampling keeps the wall
        # bounded; accept correctness is covered by the connects)
        for s in socks[::100] + [socks[0], socks[-1]]:
            assert _ping(s)["result"] == "pong"
        per_conn_kb = (_proc_rss_kb(proc.pid) - rss_before) / conns
        # an order-of-magnitude tripwire, not a microbenchmark: the
        # asyncio session state must stay in the tens of KB
        assert per_conn_kb < 100.0, f"{per_conn_kb:.1f} KB per connection"
        # the listener still accepts beyond 10⁴
        extra = socket.create_connection(("127.0.0.1", port), timeout=30)
        assert _ping(extra)["result"] == "pong"
        extra.close()
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        proc.kill()
        proc.wait(timeout=10)


@pytest.mark.slow
def test_frontdoor_accepts_two_thousand_connections(tmp_path):
    """The routing front door is thread-per-connection: pin accept
    behavior and responsiveness at 2×10³ concurrent clients (its
    documented scale ceiling sits below the asyncio server's)."""
    from fluidframework_tpu.service.frontdoor import FrontDoor

    fd = FrontDoor(str(tmp_path / "fd"), n_shards=1,
                   spawn="thread").start()
    socks = []
    try:
        for _ in range(2000):
            socks.append(socket.create_connection(
                ("127.0.0.1", fd.port), timeout=30))
        for s in socks[::50] + [socks[0], socks[-1]]:
            assert _ping(s)["result"] == "pong"
        assert fd.stats()["relay"]["sessions"] == 2000
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        fd.close()
