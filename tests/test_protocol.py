"""Protocol core: sequencer stamping, MSN tracking, dedup, summary trees."""

from fluidframework_tpu.protocol import (
    MessageType,
    RawOperation,
    Sequencer,
    SummaryStorage,
    SummaryTree,
    canonical_json,
)


def _op(client, client_seq, ref_seq, contents=None):
    return RawOperation(
        client_id=client,
        client_seq=client_seq,
        ref_seq=ref_seq,
        type=MessageType.OP,
        contents=contents,
    )


def test_sequencer_stamps_total_order():
    seq = Sequencer()
    seq.connect("A")
    seq.connect("B")
    m1 = seq.submit(_op("A", 1, 0, "x"))
    m2 = seq.submit(_op("B", 1, 0, "y"))
    m3 = seq.submit(_op("A", 2, m1.seq, "z"))
    assert [m.seq for m in (m1, m2, m3)] == [3, 4, 5]  # 2 JOINs first
    assert m3.ref_seq == m1.seq


def test_sequencer_min_seq_is_min_of_ref_seqs_and_monotone():
    seq = Sequencer()
    seq.connect("A")
    seq.connect("B")
    base = seq.seq
    mA = seq.submit(_op("A", 1, base))
    assert mA.min_seq <= base
    # B catches up to head; A still at base → MSN pinned at base.
    seq.update_ref_seq("B", mA.seq)
    m2 = seq.submit(_op("B", 1, mA.seq))
    assert m2.min_seq == base
    # A catches up → MSN advances.
    seq.update_ref_seq("A", m2.seq)
    m3 = seq.submit(_op("B", 2, m2.seq))
    assert m3.min_seq == m2.seq
    msns = [m.min_seq for m in seq.log]
    assert msns == sorted(msns)  # MSN is monotone


def test_sequencer_dedups_resubmits_by_client_seq():
    seq = Sequencer()
    seq.connect("A")
    m1 = seq.submit(_op("A", 1, 0))
    assert m1 is not None
    assert seq.submit(_op("A", 1, 0)) is None  # duplicate clientSeq dropped
    m2 = seq.submit(_op("A", 2, 0))
    assert m2.seq == m1.seq + 1


def test_sequencer_disconnect_releases_msn():
    seq = Sequencer()
    seq.connect("A")
    seq.connect("B")
    base = seq.seq
    for i in range(3):
        seq.submit(_op("A", i + 1, base))
    head = seq.seq
    seq.update_ref_seq("A", head)
    # B never advanced; disconnecting B lets MSN move to A's ref_seq.
    seq.disconnect("B")
    m = seq.submit(_op("A", 10, head))
    assert m.min_seq == head


def test_summary_tree_digest_is_canonical_and_content_addressed():
    t1 = SummaryTree()
    t1.add_json_blob("header", {"b": 2, "a": 1})
    t2 = SummaryTree()
    t2.add_blob("header", canonical_json({"a": 1, "b": 2}))
    assert t1.digest() == t2.digest()  # key order doesn't matter
    t3 = SummaryTree()
    t3.add_json_blob("header", {"a": 1, "b": 3})
    assert t1.digest() != t3.digest()


def test_summary_storage_roundtrip_and_latest():
    store = SummaryStorage()
    t1 = SummaryTree().add_json_blob("header", {"v": 1})
    t2 = SummaryTree().add_json_blob("header", {"v": 2})
    store.upload("doc", t1, ref_seq=10)
    h2 = store.upload("doc", t2, ref_seq=20)
    latest, ref_seq = store.latest("doc")
    assert ref_seq == 20
    assert latest.digest() == h2 == t2.digest()
    assert store.read(h2).blob_bytes("header") == canonical_json({"v": 2})
