"""Nightly fuzz tier — the long, env-gated campaign (VERDICT r4 item 6).

The inline fuzz suite (test_fuzz.py) is breadth at ~30-op scale; this tier
is the same convergence harness scaled to hundreds of rounds x many seeds
x MIXED specs, with oracle-vs-kernel digest asserts on every generated
log, warm reloads mid-stream, and a loader-level stash/rehydrate campaign.

Gated off by default (CI latency); run it with e.g.:

    FF_FUZZ_ROUNDS=150 FF_FUZZ_SEEDS=100 \
        python -m pytest tests/test_fuzz_nightly.py -q

- ``FF_FUZZ_ROUNDS`` (required): rounds per seed for the DDS campaign.
- ``FF_FUZZ_SEEDS`` (default 100): seed count.

Any divergence prints its seed; minimize by re-running that seed alone
and shrinking ROUNDS, then pin the shrunken log as a directed test.
The round-5 documented run is recorded in BASELINE.md (§nightly fuzz).
"""

import os
import random

import pytest

from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.ops.map_kernel import MapDocInput, replay_map_batch
from fluidframework_tpu.ops.matrix_kernel import (
    MatrixDocInput,
    replay_matrix_batch,
)
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    replay_mergetree_batch,
)
from fluidframework_tpu.testing.fuzz import (
    DirectoryFuzzSpec,
    MapFuzzSpec,
    MatrixFuzzSpec,
    QueueFuzzSpec,
    RegisterFuzzSpec,
    StringFuzzSpec,
    run_fuzz,
)
from fluidframework_tpu.testing.mocks import channel_log

ROUNDS = int(os.environ.get("FF_FUZZ_ROUNDS", "0"))
SEEDS = int(os.environ.get("FF_FUZZ_SEEDS", "100"))
#: campaign seed offset — vary across sessions to broaden coverage
SEED_BASE = int(os.environ.get("FF_FUZZ_SEED_BASE", "90000"))

pytestmark = pytest.mark.skipif(
    ROUNDS <= 0,
    reason="nightly fuzz tier: set FF_FUZZ_ROUNDS (e.g. 150)",
)


def _spec_for(seed: int):
    """Deterministic mixed-spec schedule: every string feature combination
    appears across the seed range, plus map/directory/matrix legs."""
    r = seed % 10
    if r < 5:  # half the seeds hammer the merge tree (the riskiest kernel)
        return "string", StringFuzzSpec(
            annotate=True,
            intervals=(seed % 2 == 0),
            obliterate=(seed % 3 != 0),
        )
    if r < 6:
        return "map", MapFuzzSpec()
    if r < 7:
        return "directory", DirectoryFuzzSpec()
    if r < 8:
        # seed % 10 == 7 forces seed odd, so alternate on the tens digit
        # (seed % 2 would pick registers every time — review r5).
        return ("register", RegisterFuzzSpec()) if (seed // 10) % 2 \
            else ("queue", QueueFuzzSpec())
    return "matrix", MatrixFuzzSpec(fww=(seed % 4 == 3))


def _warm_reload_hook(kind, spec, rng):
    """on_sync hook: occasionally summarize a replica and attach a FRESH
    client loaded from that summary mid-stream (warm reload) — it must
    converge with the veterans from then on."""
    joined = []

    def hook(factory, replicas):
        if len(replicas) >= 7 or rng.random() > 0.25:
            return
        summary = replicas[0].summarize()
        fresh = spec.create(replicas[0].id)
        fresh.load(summary)
        client = factory.create_client(f"warm{len(joined)}")
        replica = client.attach(fresh)
        # The new client's own JOIN sequenced (and delivered to veterans)
        # BEFORE the attach, so the fresh replica missed that window
        # advance; a real loader replays its JOIN from the catch-up tail.
        # Without this, a summarize racing the join diverges on header seq
        # (fuzz-found at seed 90024, 40 rounds).
        advance = getattr(fresh, "advance", None)
        if advance is not None:
            advance(factory.sequencer.seq, factory.sequencer.min_seq)
        replicas.append(replica)
        joined.append(client.client_id)

    return hook


def _kernel_parity(kind, log, oracle_digest, final_seq, final_msn):
    """Oracle-vs-kernel digest assert on the campaign's generated log —
    the device path must agree with the CPU oracle on every stream the
    fuzzer can produce (string / map / matrix kernels; directory folds
    host-side only).  ``final_seq``/``final_msn`` are the CONTAINER head
    window (what the catch-up service passes), not the last channel op's."""
    if not log:
        return
    if kind == "string":
        [s] = replay_mergetree_batch([MergeTreeDocInput(
            doc_id="fuzz", ops=log, final_seq=final_seq,
            final_msn=final_msn,
        )])
    elif kind == "map":
        [s] = replay_map_batch([MapDocInput(doc_id="fuzz", ops=log)])
    elif kind == "matrix":
        [s] = replay_matrix_batch([MatrixDocInput(
            doc_id="fuzz", ops=log, final_seq=final_seq,
            final_msn=final_msn,
        )])
    else:
        return  # directory: no device kernel (host-side by design)
    assert s.digest() == oracle_digest, f"{kind}: kernel != oracle"


@pytest.mark.parametrize("seed", range(SEEDS))
def test_nightly_dds_campaign(seed):
    kind, spec = _spec_for(seed)
    rng = random.Random(seed * 31 + 7)
    n_clients = 3 + seed % 3
    rounds = ROUNDS if kind == "string" else max(20, ROUNDS // 2)
    replicas, factory = run_fuzz(
        spec,
        seed=SEED_BASE + seed,
        n_clients=n_clients,
        rounds=rounds,
        sync_every=2 + seed % 7,
        on_sync=_warm_reload_hook(kind, spec, rng),
    )
    # Fresh catch-up oracle over the sequenced log == the live replicas
    # (convergence already asserted inside run_fuzz), then the kernel.
    # The fresh replay must end at the CONTAINER head window (live
    # replicas advanced past trailing JOINs / MSN ticks the channel log
    # does not carry).
    log = channel_log(factory, "fuzz")
    if not log:
        return
    head_seq = factory.sequencer.seq
    head_msn = factory.sequencer.min_seq
    oracle = spec.create("fuzz")
    for m in log:
        oracle.process(m, local=False)
    advance = getattr(oracle, "advance", None)
    if advance is not None:
        advance(head_seq, head_msn)
    oracle_digest = oracle.summarize().digest()
    assert oracle_digest == replicas[0].summarize().digest(), (
        f"seed={seed}: fresh catch-up != live replica"
    )
    _kernel_parity(kind, log, oracle_digest, head_seq, head_msn)


# --- loader-level stash / rehydrate campaign ---------------------------------


def _build_doc(runtime):
    ds = runtime.create_datastore("ds")
    ds.create_channel("sequence-tpu", "text")
    ds.create_channel("map-tpu", "meta")


def _random_edit(rng, container):
    ds = container.runtime.get_datastore("ds")
    text = ds.get_channel("text")
    n = len(text.text)
    r = rng.random()
    if r < 0.5 or n < 4:
        text.insert_text(rng.randint(0, n),
                         "".join(rng.choice("abcdef ")
                                 for _ in range(rng.randint(1, 6))))
    elif r < 0.7:
        start = rng.randint(0, n - 2)
        text.remove_range(start, min(n, start + rng.randint(1, 5)))
    elif r < 0.85:
        start = rng.randint(0, n - 2)
        text.annotate_range(start, min(n, start + rng.randint(1, 5)),
                            {"w": rng.randint(0, 3)})
    else:
        ds.get_channel("meta").set(f"k{rng.randint(0, 5)}", rng.randint(0, 99))


@pytest.mark.parametrize("seed", range(max(4, SEEDS // 8)))
def test_nightly_stash_rehydrate_campaign(seed):
    """Seeded loader sessions: two clients edit with random drains; the
    second client repeatedly closes with UNACKED pending ops and
    rehydrates into a new session (exact stash round-trip); periodic
    central catch-up folds must match the live replicas byte-for-byte."""
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService

    rng = random.Random(5_000 + seed)
    service = LocalOrderingService()
    loader = Loader(LocalDocumentServiceFactory(service))
    a = loader.create("doc", "alice", _build_doc)
    b = loader.resolve("doc", "bob0")
    generation = 0
    for step in range(ROUNDS):
        _random_edit(rng, a if rng.random() < 0.5 else b)
        if rng.random() < 0.4:
            a.drain()
        if rng.random() < 0.4:
            b.drain()
        if rng.random() < 0.06:
            # stash bob mid-flight (possibly with pending ops) and
            # rehydrate into a fresh session
            stash = b.close_and_get_pending_state()
            generation += 1
            b = loader.resolve("doc", f"bob{generation}",
                               pending_state=stash)
        if rng.random() < 0.05:
            CatchupService(service).catch_up()
    for c in (a, b):
        c.drain()
    # let both replicas fold every sequenced op (incl. the other's JOINs)
    head = service.endpoint("doc").head_seq
    for _ in range(64):
        a.drain()
        b.drain()
        if a.runtime.ref_seq == b.runtime.ref_seq == head:
            break
    assert a.runtime.ref_seq == b.runtime.ref_seq == head
    da = a.runtime.summarize().digest()
    assert da == b.runtime.summarize().digest(), f"seed={seed}: diverged"
    # a fresh catch-up load (central fold + empty tail) agrees too
    CatchupService(service).catch_up()
    fresh = loader.resolve("doc", client_id=None)
    assert fresh.runtime.summarize().digest() == da
