"""Device matrix kernel vs CPU oracle: byte-identical summaries.

North-star config #4 acceptance gate: fuzz-generated SharedMatrix op logs
replayed through the dual-axis device fold + host cell fold must produce the
exact canonical summary bytes of the oracle — same permutation tie-breaks,
same handle resolution, same LWW/FWW winners, same normalization.
"""

import pytest

from fluidframework_tpu.dds import SharedMatrix
from fluidframework_tpu.ops.matrix_kernel import (
    MatrixDocInput,
    replay_matrix_batch,
)
from fluidframework_tpu.testing import MockContainerRuntimeFactory
from fluidframework_tpu.testing.fuzz import MatrixFuzzSpec, run_fuzz
from fluidframework_tpu.testing.mocks import channel_log


def _doc_from_fuzz(factory, doc_id="fuzz", base_summary=None,
                   min_seq_exclusive=0):
    return MatrixDocInput(
        doc_id=doc_id,
        ops=channel_log(factory, "fuzz", min_seq_exclusive=min_seq_exclusive),
        base_summary=base_summary,
        final_seq=factory.sequencer.seq,
        final_msn=factory.sequencer.min_seq,
    )


@pytest.mark.parametrize("seed", range(8))
def test_matrix_kernel_matches_oracle_on_fuzz_logs(seed):
    replicas, factory = run_fuzz(
        MatrixFuzzSpec(), seed=seed, n_clients=3, rounds=20
    )
    oracle = replicas[0].summarize()
    [summary] = replay_matrix_batch([_doc_from_fuzz(factory)])
    assert summary.digest() == oracle.digest(), (
        f"seed={seed}: kernel body "
        f"{summary.blob_bytes('body')!r} != oracle "
        f"{oracle.blob_bytes('body')!r}"
    )


@pytest.mark.parametrize("seed", range(4))
def test_matrix_kernel_matches_oracle_fww(seed):
    replicas, factory = run_fuzz(
        MatrixFuzzSpec(fww=True), seed=700 + seed, n_clients=3, rounds=20
    )
    oracle = replicas[0].summarize()
    [summary] = replay_matrix_batch([_doc_from_fuzz(factory)])
    assert summary.digest() == oracle.digest()


def test_matrix_kernel_batches_docs_of_different_sizes():
    docs, oracle_digests = [], []
    for seed in (80, 81, 82):
        replicas, factory = run_fuzz(
            MatrixFuzzSpec(), seed=seed, n_clients=2, rounds=5 + 5 * (seed % 3)
        )
        docs.append(_doc_from_fuzz(factory, doc_id=f"d{seed}"))
        oracle_digests.append(replicas[0].summarize().digest())
    summaries = replay_matrix_batch(docs)
    assert [s.digest() for s in summaries] == oracle_digests


def test_matrix_kernel_replays_tail_from_base_summary():
    """The flagship catch-up shape: summary at seq S + op tail."""
    replicas, factory = run_fuzz(
        MatrixFuzzSpec(), seed=90, n_clients=3, rounds=12
    )
    base = replicas[0].summarize()
    base_seq = factory.sequencer.seq
    # Keep editing after the summary point.
    rng_ops = [
        lambda m: m.insert_rows(0, 1),
        lambda m: m.set_cell(0, 0, "tail1"),
        lambda m: m.remove_cols(0, 1) if m.col_count > 1 else None,
        lambda m: m.set_cell(m.row_count - 1, m.col_count - 1, "tail2"),
    ]
    for i, fn in enumerate(rng_ops):
        fn(replicas[i % len(replicas)])
    factory.process_all_messages()
    oracle = replicas[0].summarize()
    [summary] = replay_matrix_batch(
        [_doc_from_fuzz(factory, base_summary=base,
                        min_seq_exclusive=base_seq)]
    )
    assert summary.digest() == oracle.digest(), (
        summary.blob_bytes("body"), oracle.blob_bytes("body")
    )


def test_matrix_kernel_directed_concurrent_structure():
    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(SharedMatrix("fuzz"))
    b = factory.create_client("B").attach(SharedMatrix("fuzz"))
    a.insert_rows(0, 2)
    a.insert_cols(0, 2)
    factory.process_all_messages()
    a.set_cell(1, 1, "x")
    b.insert_rows(1, 1)   # concurrent with the cell write
    a.remove_rows(0, 1)
    b.set_cell(0, 0, "y")
    factory.process_all_messages()
    oracle = a.summarize()
    assert b.summarize().digest() == oracle.digest()
    [summary] = replay_matrix_batch([_doc_from_fuzz(factory)])
    assert summary.digest() == oracle.digest()
