"""Document-sharded replay on a virtual 8-device mesh (conftest forces
XLA_FLAGS=--xla_force_host_platform_device_count=8 on CPU).

Validates: even/uneven doc counts shard correctly, results are byte-identical
to both the single-chip device path and the CPU oracle, and the compiled step
really spans all mesh devices.
"""

import jax
import pytest

from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    replay_mergetree_batch,
)
from fluidframework_tpu.parallel import (
    dcn_mesh,
    doc_mesh,
    replay_mergetree_sharded,
)
from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz
from fluidframework_tpu.testing.mocks import channel_log


@pytest.fixture(scope="module")
def fuzz_docs():
    docs, oracle_digests = [], []
    for seed in range(11):  # deliberately not a multiple of 8
        replicas, factory = run_fuzz(
            StringFuzzSpec(), seed=300 + seed, n_clients=2, rounds=5 + seed
        )
        docs.append(
            MergeTreeDocInput(
                doc_id=f"doc{seed}",
                ops=channel_log(factory, "fuzz"),
                final_seq=factory.sequencer.seq,
                final_msn=factory.sequencer.min_seq,
            )
        )
        oracle_digests.append(replicas[0].summarize().digest())
    return docs, oracle_digests


def test_mesh_spans_eight_devices():
    mesh = doc_mesh()
    assert mesh.size == 8, f"expected 8 virtual devices, got {mesh.size}"


def test_sharded_replay_matches_oracle_and_single_chip(fuzz_docs):
    docs, oracle_digests = fuzz_docs
    mesh = doc_mesh()
    stats: dict = {}
    sharded = replay_mergetree_sharded(docs, mesh=mesh, stats=stats)
    assert [s.digest() for s in sharded] == oracle_digests
    single_stats: dict = {}
    single = replay_mergetree_batch(docs, single_stats)
    assert [s.digest() for s in single] == oracle_digests
    # The multichip path reports the same device-vs-oracle split as the
    # single-chip batch entry point (advisor, round 5: sharded replay
    # silently dropped its stats).
    assert stats.get("device_docs", 0) + stats.get("fallback_docs", 0) \
        == len(docs)
    assert stats == single_stats


def test_sharded_replay_single_doc_pads_to_mesh(fuzz_docs):
    docs, oracle_digests = fuzz_docs
    [summary] = replay_mergetree_sharded(docs[:1], mesh=doc_mesh())
    assert summary.digest() == oracle_digests[0]


def test_graft_entry_contract():
    """The driver's integration points: entry() compiles single-device;
    dryrun_multichip() runs the sharded step on the virtual mesh."""
    import importlib.util, pathlib

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__", pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn, example_args = mod.entry()
    out = jax.jit(fn)(*example_args)
    assert jax.tree.leaves(out), "entry() produced no outputs"
    mod.dryrun_multichip(8)


def test_dcn_mesh_shape_and_validation():
    mesh = dcn_mesh(2)
    assert mesh.axis_names == ("slice", "docs")
    assert mesh.devices.shape == (2, 4)
    mesh4 = dcn_mesh(4)
    assert mesh4.devices.shape == (4, 2)
    with pytest.raises(ValueError):
        dcn_mesh(3)  # 8 devices don't split into 3 slices
    with pytest.raises(ValueError):
        dcn_mesh(0)


def test_dcn_mesh_rejects_rows_straddling_hardware_slices():
    class FakeDev:
        def __init__(self, i, slice_index):
            self.id = i
            self.slice_index = slice_index

    # 4 hardware slices of 2 devices: dcn_mesh(2) would put two hardware
    # slices in one mesh row (DCN inside the "ICI" axis) — must reject.
    devs = [FakeDev(i, i // 2) for i in range(8)]
    with pytest.raises(ValueError, match="straddle a DCN boundary"):
        dcn_mesh(2, devs)


def test_dcn_sharded_replay_matches_oracle(fuzz_docs):
    """Multi-slice scale-out: the 2-D (slice, docs) mesh — documents
    data-parallel across slices (DCN) and chips (ICI) — produces
    byte-identical summaries to the oracle, for every slice split."""
    docs, oracle_digests = fuzz_docs
    for n_slices in (2, 4):
        sharded = replay_mergetree_sharded(docs, mesh=dcn_mesh(n_slices))
        assert [s.digest() for s in sharded] == oracle_digests


def test_odd_mesh_size_shards_map_and_matrix():
    """Non-power-of-two device counts (e.g. 5): the map kernel's flat op
    axis and the matrix kernel's [2D] row axis must still split evenly
    (fuzz/dryrun-found: pow2 buckets and the docs//2 pad both assumed even
    mesh sizes)."""
    from fluidframework_tpu.ops.map_kernel import (
        MapDocInput,
        replay_map_batch,
    )
    from fluidframework_tpu.ops.matrix_kernel import (
        MatrixDocInput,
        replay_matrix_batch,
    )
    from fluidframework_tpu.parallel import (
        replay_map_sharded,
        replay_matrix_sharded,
    )
    from fluidframework_tpu.testing.fuzz import MapFuzzSpec, MatrixFuzzSpec

    mesh = doc_mesh(jax.devices()[:5])
    map_docs, mx_docs = [], []
    for seed in range(3):
        _r, factory = run_fuzz(MapFuzzSpec(), seed=800 + seed,
                               n_clients=2, rounds=8)
        map_docs.append(
            MapDocInput(doc_id=f"m{seed}", ops=channel_log(factory, "fuzz"))
        )
        _r, factory = run_fuzz(MatrixFuzzSpec(), seed=800 + seed,
                               n_clients=2, rounds=8)
        mx_docs.append(MatrixDocInput(
            doc_id=f"mx{seed}", ops=channel_log(factory, "fuzz"),
            final_seq=factory.sequencer.seq,
            final_msn=factory.sequencer.min_seq,
        ))
    assert [s.digest() for s in replay_map_sharded(map_docs, mesh=mesh)] == \
        [s.digest() for s in replay_map_batch(map_docs)]
    assert [s.digest()
            for s in replay_matrix_sharded(mx_docs, mesh=mesh)] == \
        [s.digest() for s in replay_matrix_batch(mx_docs)]


def test_dcn_sharded_map_and_matrix_match_oracle():
    from fluidframework_tpu.ops.map_kernel import MapDocInput
    from fluidframework_tpu.parallel import (
        replay_map_sharded,
        replay_matrix_sharded,
    )
    from fluidframework_tpu.ops.matrix_kernel import MatrixDocInput
    from fluidframework_tpu.testing.fuzz import MapFuzzSpec, MatrixFuzzSpec

    mesh = dcn_mesh(2)
    map_docs, map_digests = [], []
    mx_docs, mx_digests = [], []
    for seed in range(3):
        replicas, factory = run_fuzz(
            MapFuzzSpec(), seed=700 + seed, n_clients=2, rounds=8
        )
        map_docs.append(
            MapDocInput(doc_id=f"m{seed}", ops=channel_log(factory, "fuzz"))
        )
        map_digests.append(replicas[0].summarize().digest())
        replicas, factory = run_fuzz(
            MatrixFuzzSpec(), seed=700 + seed, n_clients=2, rounds=8
        )
        mx_docs.append(MatrixDocInput(
            doc_id=f"mx{seed}", ops=channel_log(factory, "fuzz"),
            final_seq=factory.sequencer.seq,
            final_msn=factory.sequencer.min_seq,
        ))
        mx_digests.append(replicas[0].summarize().digest())
    assert [s.digest()
            for s in replay_map_sharded(map_docs, mesh=mesh)] == map_digests
    assert [s.digest()
            for s in replay_matrix_sharded(mx_docs, mesh=mesh)] == mx_digests


def test_tree_sharded_matches_oracle():
    from fluidframework_tpu.ops.tree_kernel import TreeDocInput
    from fluidframework_tpu.parallel import replay_tree_sharded
    from tests.test_tree_kernel import run_fuzz_doc

    docs, oracle_digests = [], []
    for seed in range(5):  # not a multiple of 8: exercises padding
        _f, trees, log, fs, fm = run_fuzz_doc(600 + seed, steps=30)
        docs.append(
            TreeDocInput("tree", ops=log, final_seq=fs, final_msn=fm)
        )
        oracle_digests.append(trees[0].summarize().digest())
    sharded = replay_tree_sharded(docs, mesh=doc_mesh())
    assert [s.digest() for s in sharded] == oracle_digests


def test_map_sharded_matches_oracle_and_single_chip():
    from fluidframework_tpu.ops.map_kernel import (
        MapDocInput,
        replay_map_batch,
    )
    from fluidframework_tpu.parallel import replay_map_sharded
    from fluidframework_tpu.testing.fuzz import MapFuzzSpec

    docs, oracle_digests = [], []
    for seed in range(5):
        replicas, factory = run_fuzz(
            MapFuzzSpec(), seed=500 + seed, n_clients=2, rounds=8 + seed
        )
        docs.append(
            MapDocInput(doc_id=f"m{seed}", ops=channel_log(factory, "fuzz"))
        )
        oracle_digests.append(replicas[0].summarize().digest())
    sharded = replay_map_sharded(docs, mesh=doc_mesh())
    assert [s.digest() for s in sharded] == oracle_digests
    single = replay_map_batch(docs)
    assert [s.digest() for s in single] == oracle_digests


def test_matrix_sharded_matches_oracle_and_single_chip():
    from fluidframework_tpu.ops.matrix_kernel import (
        MatrixDocInput,
        replay_matrix_batch,
    )
    from fluidframework_tpu.parallel import replay_matrix_sharded
    from fluidframework_tpu.testing.fuzz import MatrixFuzzSpec

    docs, oracle_digests = [], []
    for seed in range(5):  # 5 docs -> [10] axis rows over 8 devices: uneven
        replicas, factory = run_fuzz(
            MatrixFuzzSpec(), seed=600 + seed, n_clients=2, rounds=8 + seed
        )
        docs.append(
            MatrixDocInput(
                doc_id=f"mx{seed}", ops=channel_log(factory, "fuzz"),
                final_seq=factory.sequencer.seq,
                final_msn=factory.sequencer.min_seq,
            )
        )
        oracle_digests.append(replicas[0].summarize().digest())
    sharded = replay_matrix_sharded(docs, mesh=doc_mesh())
    assert [s.digest() for s in sharded] == oracle_digests
    single = replay_matrix_batch(docs)
    assert [s.digest() for s in single] == oracle_digests


def _graft_entry():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        pathlib.Path(__file__).parent.parent / "__graft_entry__.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_hard_mergetree_semantics_sharded_match_oracle():
    """The dryrun's hard-semantics docs — deep-lag obliterate arrival
    kill, overlap removers, annotate races, lagged fuzz logs, warm
    obliterate base — must be RIGHT (CPU-oracle parity), not merely
    consistent between sharded and single-device (VERDICT r3 weak #4)."""
    from fluidframework_tpu.dds.sequence import SharedString

    mod = _graft_entry()
    docs = mod._hard_mergetree_docs()
    directed = {d.doc_id: d for d in docs}

    # Directed deep-lag semantics, asserted on the oracle first: the
    # pos-3 insert dies inside the obliterated range, the pos-1 endpoint
    # insert survives.
    oracle = SharedString("deep-lag")
    for m in directed["deep-lag"].ops:
        oracle.process(m, local=False)
    assert oracle.text == "aYYf", oracle.text

    oracle_digests = []
    for doc in docs:
        replica = SharedString(doc.doc_id)
        if doc.base_records is not None:
            continue  # warm docs: checked sharded==single below; their
            # oracle parity is pinned by the kernel warm-start tests
        for m in doc.ops:
            replica.process(m, local=False)
        oracle_digests.append(replica.summarize().digest())

    cold_docs = [d for d in docs if d.base_records is None]
    sharded = replay_mergetree_sharded(cold_docs, mesh=doc_mesh())
    assert [s.digest() for s in sharded] == oracle_digests
    single = replay_mergetree_batch(cold_docs)
    assert [s.digest() for s in single] == oracle_digests

    # Warm docs: sharded fold of base+tail == single-device fold (their
    # oracle parity is pinned by the kernel warm-start tests).
    warm_docs = [d for d in docs if d.base_records is not None]
    assert warm_docs, "hard docs must include a warm obliterate doc"
    warm_sharded = replay_mergetree_sharded(warm_docs, mesh=doc_mesh())
    warm_single = replay_mergetree_batch(warm_docs)
    assert [s.digest() for s in warm_sharded] == \
        [s.digest() for s in warm_single]


def test_hard_tree_and_matrix_docs_sharded_match_single():
    from fluidframework_tpu.ops.matrix_kernel import replay_matrix_batch
    from fluidframework_tpu.ops.tree_kernel import replay_tree_batch
    from fluidframework_tpu.parallel import (
        replay_matrix_sharded,
        replay_tree_sharded,
    )

    mod = _graft_entry()
    tree_docs = mod._hard_tree_docs()
    assert any(d.base_summary is not None for d in tree_docs)
    t_sharded = replay_tree_sharded(tree_docs, mesh=doc_mesh())
    t_single = replay_tree_batch(tree_docs)
    assert [s.digest() for s in t_sharded] == \
        [s.digest() for s in t_single]

    mx_docs = mod._hard_matrix_docs()
    assert any(d.base_summary is not None for d in mx_docs)
    m_sharded = replay_matrix_sharded(mx_docs, mesh=doc_mesh())
    m_single = replay_matrix_batch(mx_docs)
    assert [s.digest() for s in m_sharded] == \
        [s.digest() for s in m_single]
