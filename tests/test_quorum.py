"""Quorum proposals: propose/accept over the sequenced stream.

Acceptance rule (protocol/quorum.py): a proposal sequenced at S commits
when the MSN reaches S.  These tests drive real runtimes through the
ordering service: convergence under concurrent proposers, survival across
summarize/reload, and byte-parity of the catch-up service's protocol fold.
"""

import random

from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service import LocalOrderingService
from fluidframework_tpu.service.catchup import CatchupService


def _connected(service, doc_id, client_id, with_text=True):
    if not service.has_document(doc_id):
        ep = service.create_document(doc_id)
    else:
        ep = service.endpoint(doc_id)
    rt = ContainerRuntime()
    if with_text:
        rt.create_datastore("ds").create_channel("sequence-tpu", "text")
    rt.connect(ep, client_id)
    rt.drain()
    return rt, ep


def _pump(runtimes, rounds=2):
    """Everyone submits a trivial op (advancing their ref_seq at the
    sequencer) and drains — the MSN catches up to the head."""
    for _ in range(rounds):
        for rt in runtimes:
            text = rt.get_datastore("ds").get_channel("text")
            text.insert_text(len(text.text), ".")
        for rt in runtimes:
            rt.drain()


def test_proposal_accepts_when_msn_passes():
    service = LocalOrderingService()
    a, ep = _connected(service, "doc", "alice")
    b, _ = _connected(service, "doc", "bob")
    a.drain()
    b.drain()

    a.propose("code", {"package": "app", "version": "2.0"})
    a.drain()
    b.drain()
    # sequenced but pending: bob's ref_seq hasn't passed the proposal yet
    assert not a.quorum_proposals.has("code")
    assert a.quorum_proposals.pending()

    _pump([a, b])
    assert a.quorum_proposals.get("code") == \
        b.quorum_proposals.get("code") == \
        {"package": "app", "version": "2.0"}
    assert not a.quorum_proposals.pending()


def test_concurrent_proposers_converge_to_the_later_seq():
    service = LocalOrderingService()
    a, _ = _connected(service, "doc", "alice")
    b, _ = _connected(service, "doc", "bob")
    a.drain()
    b.drain()

    # Both propose before either drains: both sequence; the later seq wins
    # the final value on every replica.
    a.propose("code", "A")
    b.propose("code", "B")
    a.drain()
    b.drain()
    _pump([a, b])
    assert a.quorum_proposals.get("code") == b.quorum_proposals.get("code")
    # sequence order decided it: whichever proposal sequenced second
    assert a.quorum_proposals.get("code") in ("A", "B")
    assert a.summarize().digest() == b.summarize().digest()


def test_pending_proposal_survives_summarize_and_reload():
    service = LocalOrderingService()
    a, ep = _connected(service, "doc", "alice")
    b, _ = _connected(service, "doc", "bob")
    a.drain()
    b.drain()
    a.propose("flag", 7)
    a.drain()
    b.drain()
    assert a.quorum_proposals.pending()  # MSN still behind

    snapshot = a.summarize()
    loaded = ContainerRuntime()
    loaded_seq = loaded.load(snapshot)
    assert loaded.quorum_proposals.pending() == a.quorum_proposals.pending()

    # the live replicas advance the MSN; the loaded one replays the tail
    _pump([a, b])
    for msg in ep.deltas(from_seq=loaded_seq):
        loaded.process(msg)
    assert loaded.quorum_proposals.get("flag") == 7
    assert a.quorum_proposals.get("flag") == 7
    assert loaded.summarize().digest() == a.summarize().digest()


def test_catchup_service_folds_proposals_byte_identically():
    service = LocalOrderingService()
    a, _ = _connected(service, "doc", "alice")
    b, _ = _connected(service, "doc", "bob")
    a.drain()
    b.drain()
    service.storage.upload("doc", a.summarize(), a.ref_seq)

    a.propose("code", {"v": 1})
    a.drain()
    b.drain()
    _pump([a, b])
    b.propose("pending-key", "still-pending")  # stays pending in the tail
    a.drain()
    b.drain()

    svc = CatchupService(service)
    cpu = CatchupService(service)
    cpu._device_plan = lambda w: None
    assert svc.catch_up(upload=False) == cpu.catch_up(upload=False)
    assert svc.device_docs == 1


def test_fuzzed_proposals_converge(seed=1234):
    """Randomized interleaving of proposals and edits from 3 clients:
    every replica ends with the same accepted values and byte-identical
    summaries."""
    rng = random.Random(seed)
    service = LocalOrderingService()
    runtimes = []
    for i in range(3):
        rt, _ = _connected(service, "doc", f"client{i}")
        runtimes.append(rt)
    for rt in runtimes:
        rt.drain()

    keys = ["code", "theme", "limit"]
    for step in range(60):
        rt = rng.choice(runtimes)
        if rng.random() < 0.3:
            rt.propose(rng.choice(keys), rng.randint(0, 99))
        else:
            text = rt.get_datastore("ds").get_channel("text")
            text.insert_text(rng.randint(0, len(text.text)), "x")
        if rng.random() < 0.5:
            for r in runtimes:
                r.drain()
    _pump(runtimes, rounds=3)

    accepted = [rt.quorum_proposals.accepted() for rt in runtimes]
    assert accepted[0] == accepted[1] == accepted[2]
    assert accepted[0], "fuzz run must accept at least one proposal"
    digests = {rt.summarize().digest() for rt in runtimes}
    assert len(digests) == 1


def test_propose_does_not_jump_the_outbox_queue():
    """A proposal submitted while channel ops sit unflushed must not take a
    later client_seq and sequence first — the sequencer's dedup floor would
    silently drop the batch when it finally flushed (review-found).  The
    outbox flushes before the proposal, and proposing inside an atomic
    batch refuses."""
    import pytest

    service = LocalOrderingService()
    a, ep = _connected(service, "doc", "alice")
    b, _ = _connected(service, "doc", "bob")
    a.drain()
    b.drain()

    with pytest.raises(RuntimeError):
        with a.order_sequentially():
            a.propose("code", "nope")

    # batched edit + proposal: the edit must survive sequencing
    with a.order_sequentially():
        a.get_datastore("ds").get_channel("text").insert_text(0, "batched")
    a.propose("code", "v2")
    a.drain()
    b.drain()
    _pump([a, b])
    assert a.quorum_proposals.get("code") == "v2"
    assert b.get_datastore("ds").get_channel("text").text.startswith("batched") or \
        "batched" in b.get_datastore("ds").get_channel("text").text
    assert a.summarize().digest() == b.summarize().digest()
