"""The process boundary: TCP ordering server + network driver.

The reference's defining deployment shape — clients and the ordering
service in different processes — driven here three ways:

1. in-process server thread + network driver (fast protocol coverage);
2. the standalone server as a REAL subprocess with two concurrent editor
   CLIENT subprocesses over localhost (the multi-process convergence
   test: final texts and summary digests must agree byte-for-byte);
3. wire-version negotiation (a newer-versioned frame is refused cleanly).
"""

import json
import os
import socket
import struct
import subprocess
import sys
import textwrap
import time

import pytest

from fluidframework_tpu.drivers.network_driver import (
    NetworkDocumentServiceFactory,
    RpcError,
)
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service.server import OrderingServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_server(port, *extra_args):
    """Start the standalone server subprocess and wait for its 'listening'
    marker, skipping any warning lines other libraries print first."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.server",
         "--port", str(port), *extra_args],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    import select

    deadline = time.time() + 30
    while time.time() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [], 0.5)
        if not ready:
            if proc.poll() is not None:
                break
            continue
        line = proc.stdout.readline()
        if "listening" in line:
            return proc
        if line == "" and proc.poll() is not None:
            break
    proc.terminate()
    raise AssertionError("server never reported listening")


@pytest.fixture()
def server():
    srv = OrderingServer(port=0)
    srv.start_in_thread()
    yield srv


def test_network_driver_end_to_end(server):
    """Create over the wire, edit from two factories (two sockets), verify
    convergence and that a third, fresh load sees the merged state."""
    fa = NetworkDocumentServiceFactory(port=server.port)
    fb = NetworkDocumentServiceFactory(port=server.port)
    loader_a, loader_b = Loader(fa), Loader(fb)

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")
        ds.create_channel("map-tpu", "kv")

    a = loader_a.create("doc", "alice", build)
    b = loader_b.resolve("doc", "bob")

    a.runtime.get_datastore("ds").get_channel("text").insert_text(0, "hello ")
    a.drain()
    deadline = time.time() + 10
    while time.time() < deadline:
        b.drain()
        if b.runtime.get_datastore("ds").get_channel("text").text == "hello ":
            break
        time.sleep(0.02)
    b.runtime.get_datastore("ds").get_channel("text").insert_text(6, "world")
    b.runtime.get_datastore("ds").get_channel("kv").set("done", True)
    b.drain()
    deadline = time.time() + 10
    head = fa.resolve("doc").delta_storage.head()
    while time.time() < deadline:
        a.drain()
        b.drain()
        # Converge on the server head (ref_seq equality alone is not
        # enough: an author's optimistic pending op would leak into its
        # summary while the other replica hasn't sequenced it yet).
        if a.runtime.ref_seq == b.runtime.ref_seq == head:
            break
        time.sleep(0.02)
    assert a.runtime.get_datastore("ds").get_channel("text").text == \
        "hello world"
    assert a.runtime.ref_seq == b.runtime.ref_seq == head
    assert a.runtime.summarize().digest() == b.runtime.summarize().digest()

    fresh = Loader(NetworkDocumentServiceFactory(port=server.port)) \
        .resolve("doc")
    ds = fresh.runtime.get_datastore("ds")
    assert ds.get_channel("text").text == "hello world"
    assert ds.get_channel("kv").get("done") is True
    for f in (fa, fb):
        f.close()


def test_signals_cross_the_wire(server):
    fa = NetworkDocumentServiceFactory(port=server.port)
    fb = NetworkDocumentServiceFactory(port=server.port)
    a = Loader(fa).create("sig", "alice", lambda rt: rt.create_datastore("d"))
    b = Loader(fb).resolve("sig", "bob")
    seen = []
    b.delta_manager.subscribe_signals(seen.append)
    a.delta_manager.submit_signal({"cursor": 3})
    deadline = time.time() + 10
    while time.time() < deadline and not seen:
        time.sleep(0.02)
    assert seen and seen[0]["content"] == {"cursor": 3}
    assert seen[0]["clientId"] == "alice"
    for f in (fa, fb):
        f.close()


_CLIENT_SCRIPT = textwrap.dedent("""
    import sys, time
    sys.path.insert(0, "@REPO@")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from fluidframework_tpu.drivers.network_driver import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.loader import Loader

    port, who, word = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    loader = Loader(NetworkDocumentServiceFactory(port=port))
    if who == "alice":
        def build(rt):
            rt.create_datastore("ds").create_channel("sequence-tpu", "text")
        c = loader.create("doc", who, build)
    else:
        for _ in range(200):  # wait for alice to create
            try:
                c = loader.resolve("doc", who)
                break
            except KeyError:
                time.sleep(0.05)
        else:
            raise SystemExit("document never appeared")
    text = c.runtime.get_datastore("ds").get_channel("text")
    # interleaved edits: each client appends its word letter by letter
    for ch in word:
        text.insert_text(len(text.text), ch)
        c.drain()
        time.sleep(0.01)
    # Converge to the agreed sequence point: 2 JOINs + every letter both
    # clients wrote.  Step one message at a time so the snapshot lands on
    # that exact seq — a LEAVE sequenced by the OTHER client exiting later
    # must not leak into this digest.
    expected_head = 2 + len("alice-text") + len("bob-text")
    deadline = time.time() + 20
    while c.runtime.ref_seq < expected_head and time.time() < deadline:
        if c.runtime.drain(1) == 0:
            time.sleep(0.02)
    assert c.runtime.ref_seq == expected_head, (
        f"stopped at seq {c.runtime.ref_seq}, wanted {expected_head}"
    )
    print(json.dumps({"text": text.text,
                      "digest": c.runtime.summarize().digest()}))
""").replace("import sys, time", "import json, sys, time")


def test_multiprocess_convergence(tmp_path):
    """Server + two editing clients, each in its OWN process over
    localhost: both clients converge to the same text and byte-identical
    summaries, and the test process (a fourth process) loads the same."""
    # pick a free port, then hand it to the standalone server process
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    server_proc = _spawn_server(port)
    try:
        clients = [
            subprocess.Popen(
                [sys.executable, "-c",
                 _CLIENT_SCRIPT.replace("@REPO@", REPO),
                 str(port), who, word],
                cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for who, word in (("alice", "alice-text"), ("bob", "bob-text"))
        ]
        results = []
        for proc in clients:
            out, err = proc.communicate(timeout=120)
            assert proc.returncode == 0, f"client failed:\n{err}\n{out}"
            results.append(json.loads(out.strip().splitlines()[-1]))

        assert results[0]["text"] == results[1]["text"]
        assert results[0]["digest"] == results[1]["digest"]
        assert sorted(results[0]["text"]) == sorted("alice-text" + "bob-text")

        # The fresh load also processes the LEAVEs the exiting clients
        # sequenced after their snapshots, so quorum-bearing digests
        # legitimately differ; the replicated content must not.
        fresh = Loader(NetworkDocumentServiceFactory(port=port)) \
            .resolve("doc")
        text = fresh.runtime.get_datastore("ds").get_channel("text").text
        assert text == results[0]["text"]
    finally:
        server_proc.terminate()
        server_proc.wait(timeout=10)


def test_wire_version_negotiation(server):
    """A frame claiming a future wire version is refused with an error,
    not silently misparsed."""
    sock = socket.create_connection(("127.0.0.1", server.port), timeout=5)
    payload = json.dumps(
        {"v": 99, "id": 1, "method": "ping", "params": {}}
    ).encode()
    sock.sendall(struct.pack(">I", len(payload)) + payload)
    header = sock.recv(4)
    (length,) = struct.unpack(">I", header)
    frame = json.loads(sock.recv(length))
    assert frame["ok"] is False and "version" in frame["error"]
    sock.close()

    factory = NetworkDocumentServiceFactory(port=server.port)
    with pytest.raises((KeyError, RpcError)):
        factory.resolve("nope")
    factory.close()


def test_standalone_server_restart_recovers_documents(tmp_path):
    """Kill the standalone server and restart it over the same --dir: the
    durable op log (flushed before broadcast) plus the persisted summary
    store must recover the document for a fresh client."""
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    proc = _spawn_server(port, "--dir", str(tmp_path))
    try:
        c = Loader(NetworkDocumentServiceFactory(port=port)).create(
            "persisted", "alice",
            lambda rt: rt.create_datastore("ds").create_channel(
                "sequence-tpu", "t"),
        )
        c.runtime.get_datastore("ds").get_channel("t").insert_text(
            0, "survives restart")
        c.drain()
    finally:
        proc.terminate()
        proc.wait(timeout=10)

    proc = _spawn_server(port, "--dir", str(tmp_path))
    try:
        fresh = Loader(NetworkDocumentServiceFactory(port=port)) \
            .resolve("persisted")
        assert fresh.runtime.get_datastore("ds").get_channel("t").text == \
            "survives restart"
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_tenancy_auth_and_namespacing(server=None):
    """Riddler capability: tenants must authenticate, bad secrets are
    refused, and two tenants cannot see each other's documents."""
    from fluidframework_tpu.runtime.container import ContainerRuntime

    srv = OrderingServer(port=0, tenants={"acme": "s3cret", "beta": "pw"})
    srv.start_in_thread()

    with pytest.raises(RpcError, match="invalid tenant credentials"):
        NetworkDocumentServiceFactory(port=srv.port, tenant="acme",
                                      secret="wrong")
    # unauthenticated connections are locked out of document traffic
    anon = NetworkDocumentServiceFactory.__new__(NetworkDocumentServiceFactory)
    from fluidframework_tpu.drivers.network_driver import _RpcClient
    anon._rpc = _RpcClient("127.0.0.1", srv.port)
    anon._connections = {}
    with pytest.raises(RpcError, match="authenticate first"):
        anon.resolve("doc")
    anon.close()

    acme = NetworkDocumentServiceFactory(port=srv.port, tenant="acme",
                                         secret="s3cret")
    beta = NetworkDocumentServiceFactory(port=srv.port, tenant="beta",
                                         secret="pw")
    seeded = ContainerRuntime()
    seeded.create_datastore("ds").create_channel("sequence-tpu", "t")
    acme.create_document("doc", seeded.summarize())
    # same UNQUALIFIED name resolves only within the owning tenant
    with pytest.raises((KeyError, RpcError)):
        beta.resolve("doc")
    assert acme.resolve("doc").doc_id == "doc"

    # live traffic flows within the tenant (broadcast frames carry the
    # client-visible doc id, not the namespaced one — regression)
    a = Loader(acme).resolve("doc", "alice")
    acme2 = NetworkDocumentServiceFactory(port=srv.port, tenant="acme",
                                          secret="s3cret")
    b = Loader(acme2).resolve("doc", "bob")
    a.runtime.get_datastore("ds").get_channel("t").insert_text(0, "hi")
    a.drain()
    deadline = time.time() + 10
    while time.time() < deadline:
        b.drain()
        if b.runtime.get_datastore("ds").get_channel("t").text == "hi":
            break
        time.sleep(0.02)
    assert b.runtime.get_datastore("ds").get_channel("t").text == "hi"
    for f in (acme, acme2, beta):
        f.close()


def test_snapshot_cache_and_partial_fetch(server):
    """odsp-driver capabilities: an unchanged snapshot never re-crosses
    the wire (cache negotiation by handle), and a subtree fetches alone
    (partial snapshot virtualization)."""
    from fluidframework_tpu.runtime.container import ContainerRuntime

    factory = NetworkDocumentServiceFactory(port=server.port)
    seeded = ContainerRuntime()
    ds = seeded.create_datastore("ds")
    ds.create_channel("sequence-tpu", "t")
    svc = factory.create_document("doc", seeded.summarize())

    tree1, _seq = svc.storage.latest()
    handle = tree1.digest()
    # second latest(): the server sees our cached handle and omits the body
    raw = factory._rpc.request(
        "latest_summary",
        {"doc": "doc", "have": [handle]},
    )
    assert raw["handle"] == handle and "summary" not in raw
    tree2, _ = svc.storage.latest()
    assert tree2.digest() == handle  # served from the client cache

    # partial fetch: just the channel attributes blob's parent subtree
    sub = svc.storage.read_partial(handle, ".datastores/ds")
    assert sub.digest() == tree1.get(".datastores/ds").digest()
    factory.close()


def test_multi_instance_fan_out(tmp_path):
    """Broadcaster capability (in-proc form): two front-door server
    instances share one ordering service; clients connected to DIFFERENT
    instances see each other's ops."""
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.service import LocalOrderingService

    shared = LocalOrderingService()
    srv_a = OrderingServer(shared, port=0)
    srv_a.start_in_thread()
    srv_b = OrderingServer(shared, port=0)
    srv_b.start_in_thread()
    assert srv_a.port != srv_b.port

    fa = NetworkDocumentServiceFactory(port=srv_a.port)
    fb = NetworkDocumentServiceFactory(port=srv_b.port)
    a = Loader(fa).create("doc", "alice",
                          lambda rt: rt.create_datastore("ds").create_channel(
                              "sequence-tpu", "t"))
    b = Loader(fb).resolve("doc", "bob")
    a.runtime.get_datastore("ds").get_channel("t").insert_text(0, "fan-out")
    a.drain()
    deadline = time.time() + 10
    while time.time() < deadline:
        b.drain()
        if b.runtime.get_datastore("ds").get_channel("t").text == "fan-out":
            break
        time.sleep(0.02)
    assert b.runtime.get_datastore("ds").get_channel("t").text == "fan-out"
    for f in (fa, fb):
        f.close()


def test_tenancy_shared_content_and_multi_instance():
    """Review-found tenancy holes, regression-locked: (1) two tenants
    uploading IDENTICAL content both keep read access (content-addressed
    handles are multi-owner); (2) a tenant cannot materialize a foreign
    snapshot via incremental {"h": ...} references; (3) grants live on the
    SHARED service, so a second front-door instance honors them."""
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.service import LocalOrderingService

    shared = LocalOrderingService()
    tenants = {"acme": "a", "beta": "b"}
    s1 = OrderingServer(shared, port=0, tenants=tenants)
    s1.start_in_thread()
    s2 = OrderingServer(shared, port=0, tenants=tenants)
    s2.start_in_thread()

    acme = NetworkDocumentServiceFactory(port=s1.port, tenant="acme",
                                         secret="a")
    beta = NetworkDocumentServiceFactory(port=s1.port, tenant="beta",
                                         secret="b")

    template = ContainerRuntime()
    template.create_datastore("ds").create_channel("sequence-tpu", "t")
    tree = template.summarize()
    handle = tree.digest()

    acme.create_document("doc", tree)   # same bytes...
    beta.create_document("doc", tree)   # ...uploaded by BOTH tenants
    # (1) both tenants still read the shared-content handle
    acme_svc = acme.resolve("doc")
    beta_svc = beta.resolve("doc")
    assert acme_svc.storage.read(handle).digest() == handle
    assert beta_svc.storage.read(handle).digest() == handle

    # (2) beta edits its doc so a NEW acme-only handle exists, then tries
    # to steal it via an incremental reference
    a_rt = ContainerRuntime()
    a_rt.load(acme_svc.storage.latest()[0])
    a_rt.connect(acme_svc.connection(), "alice")
    a_rt.drain()
    a_rt.get_datastore("ds").get_channel("t").insert_text(0, "secret")
    a_rt.drain()
    secret_handle = acme_svc.storage.upload(a_rt.summarize(), a_rt.ref_seq)
    with pytest.raises(RpcError):
        beta._rpc.request("upload_summary", {
            "doc": "doc", "summary": {"v": 1, "h": secret_handle},
            "ref_seq": 99,
        })

    # (3) the SAME tenant through the OTHER front-door instance can read
    acme2 = NetworkDocumentServiceFactory(port=s2.port, tenant="acme",
                                          secret="a")
    assert acme2.resolve("doc").storage.read(secret_handle).digest() == \
        secret_handle
    for f in (acme, beta, acme2):
        f.close()


def test_server_catchup_folds_documents_centrally():
    """The "catchup" server method — the north-star path in the deployed
    shape: the service folds documents' op tails into fresh summaries
    centrally (device-routed for kernel channels), so loading clients
    start from a fresh summary and replay nothing."""
    srv = OrderingServer(port=0)
    srv.start_in_thread()
    factory = NetworkDocumentServiceFactory(port=srv.port)
    try:
        loader = Loader(factory)

        def build(rt):
            ds = rt.create_datastore("ds")
            ds.create_channel("sequence-tpu", "text")

        client = loader.create("doc", "alice", build)
        text = client.runtime.get_datastore("ds").get_channel("text")
        text.insert_text(0, "folded centrally")
        client.drain()
        head = factory.resolve("doc").delta_storage.head()
        deadline = time.time() + 10
        while time.time() < deadline and client.runtime.ref_seq != head:
            client.drain()
            time.sleep(0.02)
        want = client.runtime.summarize().digest()

        result = factory._rpc.request("catchup", {"docs": ["doc", "typo"]})
        assert "doc" in result["docs"]
        assert result["skipped"] == ["typo"]  # unknown ids are reported
        handle, seq = result["docs"]["doc"]
        assert seq == srv.service.endpoint("doc").head_seq
        assert result["deviceDocs"] + result["cpuDocs"] == 1

        # the uploaded summary IS the fresh catch-up state: a new client
        # loads it and replays nothing
        assert srv.service.storage.latest("doc")[0].digest() == handle
        fresh = Loader(
            NetworkDocumentServiceFactory(port=srv.port)
        ).resolve("doc")
        assert fresh.catchup_ops == 0
        assert fresh.runtime.summarize().digest() == want
    finally:
        factory.close()


def test_server_catchup_respects_tenancy():
    """Tenant-scoped catchup: each tenant folds only its own namespace and
    gains read grants on the produced summaries."""
    srv = OrderingServer(port=0, tenants={"acme": "s3cret", "beta": "pw"})
    srv.start_in_thread()
    fa = NetworkDocumentServiceFactory(
        port=srv.port, tenant="acme", secret="s3cret"
    )
    loader = Loader(fa)

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("map-tpu", "kv")

    fb = None
    try:
        client = loader.create("doc", "alice", build)
        client.runtime.get_datastore("ds").get_channel("kv").set("k", 1)
        client.drain()

        out = fa._rpc.request("catchup", {})  # no list: whole namespace
        assert list(out["docs"]) == ["doc"]
        handle, _seq = out["docs"]["doc"]
        # the producing tenant can read the new summary...
        assert fa._rpc.request(
            "read_summary", {"handle": handle}
        ) is not None
        # ...a foreign tenant cannot
        fb = NetworkDocumentServiceFactory(
            port=srv.port, tenant="beta", secret="pw"
        )
        try:
            fb._rpc.request("read_summary", {"handle": handle})
            raise AssertionError("foreign tenant read a granted summary")
        except Exception as exc:
            assert "denied" in str(exc) or "unknown" in str(exc) or \
                "Permission" in str(exc)
    finally:
        fa.close()
        if fb is not None:
            fb.close()


# --- odsp-parity epoch tracking (SURVEY §2.4 EpochTracker) --------------------


def test_epoch_adopted_and_stable_across_server_restart(tmp_path):
    """The storage epoch is a PERSISTED generation token: clients adopt it
    from the first latest() and a clean restart over the same --dir keeps
    it, so pinned requests keep working."""
    from fluidframework_tpu.drivers.file_driver import FileSummaryStorage

    store = str(tmp_path / "store")
    s1 = FileSummaryStorage(store)
    s2 = FileSummaryStorage(store)  # reopen: same generation
    assert s1.epoch == s2.epoch


def test_stale_epoch_partial_fetch_fails_loudly():
    """A client whose caches are pinned to a dead storage generation must
    get a LOUD epochMismatch on any storage RPC — never a silently served
    snapshot its cached deltas/handles cannot be mixed with."""
    from fluidframework_tpu.drivers.network_driver import (
        EpochMismatchError,
    )

    srv = OrderingServer(port=0)
    srv.start_in_thread()
    factory = NetworkDocumentServiceFactory(port=srv.port)
    try:
        loader = Loader(factory)

        def build(rt):
            rt.create_datastore("ds").create_channel("sequence-tpu", "text")

        c = loader.create("doc", "alice", build)
        text = c.runtime.get_datastore("ds").get_channel("text")
        text.insert_text(0, "generation one")
        c.drain()
        svc_pinned = factory.resolve("doc")  # resolved while gen-1 lives
        storage = svc_pinned.storage
        tree, _seq = storage.latest()          # adopt the epoch + cache
        assert storage._epoch == srv.service.storage.epoch
        handle = tree.digest()

        # The store is RECREATED (document wiped and reseeded): new epoch.
        from fluidframework_tpu.protocol.summary import SummaryStorage

        fresh = SummaryStorage()
        assert fresh.epoch != srv.service.storage.epoch
        old_handles = dict(srv.service.handle_tenants)
        srv.service.storage = fresh
        srv.service.handle_tenants.update(old_handles)
        seeder = Loader(NetworkDocumentServiceFactory(port=srv.port))
        c2 = seeder.create("doc2", "bob", build)
        c2.runtime.get_datastore("ds").get_channel("text") \
            .insert_text(0, "generation two")
        c2.drain()

        # Every pinned RPC fails LOUDLY — including the OP-STREAM path
        # itself: svc_pinned was resolved while gen-1 lived, so the raise
        # below comes from the actual deltas RPC, not discovery.  The
        # mismatch drops EVERY cache on the connection (central
        # invalidation at the rpc client), so the pin AND the snapshot
        # cache are gone after the FIRST loud failure, whichever path
        # observed it.
        with pytest.raises(EpochMismatchError):
            svc_pinned.delta_storage.get(0)
        assert storage._epoch is None and not storage._snapshot_cache
        # restore the pin to prove storage paths fail loudly too
        storage._epoch = "stale-" + fresh.epoch
        with pytest.raises(EpochMismatchError):
            storage.latest()
        assert storage._epoch is None and not storage._snapshot_cache
        # after the loud failure an UNPINNED request re-pins cleanly: the
        # old generation's doc simply doesn't exist in the fresh store —
        # a full reload is the only path forward, never cache mixing
        tree_after, _ = storage.latest()
        assert tree_after is None
        assert handle not in storage._snapshot_cache
    finally:
        factory.close()


def test_writer_path_adopts_epoch_on_upload():
    """A creating client (no summary fetched yet) adopts the generation
    from its first upload response, so its caches are pinned too."""
    from fluidframework_tpu.runtime.container import ContainerRuntime

    srv = OrderingServer(port=0)
    srv.start_in_thread()
    factory = NetworkDocumentServiceFactory(port=srv.port)
    try:
        rt = ContainerRuntime()
        rt.create_datastore("ds").create_channel("sequence-tpu", "t")
        svc = factory.create_document("doc", rt.summarize())
        storage = svc.storage
        assert storage._epoch is None  # fresh connection: unpinned
        storage.upload(rt.summarize(), ref_seq=0)
        assert storage._epoch == srv.service.storage.epoch
        # and the no-summary latest() on a brand-new doc pins as well
        svc2 = factory.create_document("doc2", rt.summarize())
        st2 = svc2.storage
        st2._snapshot_cache.clear()
        tree, _ = st2.latest()
        assert st2._epoch == srv.service.storage.epoch
    finally:
        factory.close()
