"""Two-tier seq-anchored catch-up cache (ISSUE 3): LRU byte accounting,
epoch invalidation, single-flight, pack-cache suffix reuse, and the
determinism contract — cache-on results byte-identical to cache-off
across golden and fuzzed corpora."""

import threading

import numpy as np
import pytest

import bench
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    replay_mergetree_batch,
)
from fluidframework_tpu.ops.pipeline import PackCache, pipelined_mergetree_replay
from fluidframework_tpu.protocol.summary import SummaryStorage, SummaryTree
from fluidframework_tpu.service import LocalOrderingService, OpLog
from fluidframework_tpu.service.catchup import CatchupService
from fluidframework_tpu.service.catchup_cache import (
    CatchupResultCache,
    tree_nbytes,
)
from tests.test_service import _seed_string_doc


def _blob_tree(payload_bytes: int) -> SummaryTree:
    tree = SummaryTree()
    tree.add_blob("body", b"x" * payload_bytes)
    return tree


# --- tier 1: LRU / byte accounting -------------------------------------------


def test_lru_byte_bound_and_eviction_order():
    one = tree_nbytes(_blob_tree(1000))
    cache = CatchupResultCache(max_bytes=3 * one)
    for i in range(3):
        cache.insert(("e", f"d{i}"), _blob_tree(1000))
    assert len(cache) == 3 and cache.current_bytes == 3 * one
    # Touch d0 so d1 becomes least-recent, then overflow by one entry.
    assert cache.lookup(("e", "d0")) is not None
    cache.insert(("e", "d3"), _blob_tree(1000))
    assert cache.lookup(("e", "d1")) is None, "LRU must evict d1 first"
    assert cache.lookup(("e", "d0")) is not None
    assert cache.lookup(("e", "d3")) is not None
    stats = cache.stats()
    assert stats["evictions"] == 1 and stats["inserts"] == 4
    assert stats["bytes"] <= cache.max_bytes


def test_oversize_entry_never_admitted():
    cache = CatchupResultCache(max_bytes=400)
    cache.insert(("e", "small"), _blob_tree(10))
    cache.insert(("e", "huge"), _blob_tree(10_000))
    assert cache.lookup(("e", "huge")) is None
    # ...and it must not have evicted the resident entry to make room.
    assert cache.lookup(("e", "small")) is not None


def test_reinsert_same_key_replaces_bytes():
    cache = CatchupResultCache(max_bytes=1 << 20)
    cache.insert(("e", "d"), _blob_tree(1000))
    before = cache.current_bytes
    cache.insert(("e", "d"), _blob_tree(2000))
    assert len(cache) == 1
    assert cache.current_bytes == before + 1000  # replaced, not added


def test_epoch_invalidation_drops_only_stale_generations():
    cache = CatchupResultCache()
    cache.insert(("old", "d0"), _blob_tree(10))
    cache.insert(("old", "d1"), _blob_tree(10))
    cache.insert(("new", "d0"), _blob_tree(10))
    assert cache.invalidate_epoch("new") == 2
    assert cache.lookup(("old", "d0")) is None
    assert cache.lookup(("new", "d0")) is not None
    assert cache.stats()["invalidations"] == 2


# --- tier 1: single-flight ----------------------------------------------------


def test_single_flight_leader_publishes_to_waiters():
    cache = CatchupResultCache()
    key = ("e", "doc")
    status, _tree = cache.begin(key)
    assert status == "lead"
    got = []
    waiter = threading.Thread(target=lambda: got.append(cache.join(key)))
    waiter.start()
    tree = _blob_tree(10)
    published = cache.finish(key, tree)
    waiter.join(timeout=10)
    assert [f.tree for f in got] == [tree]
    assert published.handle == tree.digest()  # digested once, at publish
    assert cache.stats()["waits"] == 1
    # the published entry is now a plain hit, handle included
    status, fold = cache.begin(key)
    assert status == "hit" and fold.tree is tree \
        and fold.handle == published.handle


def test_single_flight_abandon_unblocks_waiters():
    cache = CatchupResultCache()
    key = ("e", "doc")
    assert cache.begin(key)[0] == "lead"
    got = []
    waiter = threading.Thread(target=lambda: got.append(cache.join(key)))
    waiter.start()
    cache.abandon(key)
    waiter.join(timeout=10)
    assert got == [None], "abandon must wake waiters empty-handed"
    assert cache.lookup(key) is None


def test_join_timeout_returns_none_when_leader_never_finishes():
    """The bounded-wait contract (fluidrace, ISSUE 4): a leader that died
    without finish/abandon must not hang a follower — join(timeout)
    returns None once the budget elapses."""
    import time

    cache = CatchupResultCache()
    key = ("e", "doc")
    assert cache.begin(key)[0] == "lead"  # ...and the leader "crashes"
    t0 = time.monotonic()
    assert cache.join(key, timeout=0.1) is None
    assert time.monotonic() - t0 < 10
    assert cache.stats()["waits"] == 1


def test_join_timeout_pop_is_identity_guarded():
    """A timed-out waiter removes the flight it actually waited on —
    never a fresh leader's flight that replaced it in the race window
    (popping that would degrade the herd's single-flight to N folds)."""
    import time

    cache = CatchupResultCache()
    key = ("e", "doc")
    assert cache.begin(key)[0] == "lead"
    got = []
    waiter = threading.Thread(
        target=lambda: got.append(cache.join(key, timeout=0.8)))
    waiter.start()
    time.sleep(0.1)
    # Simulate the race: the stale flight vanishes (crashed leader's
    # flight reaped) and a NEW leader begins before the timeout fires.
    with cache._lock:
        cache._flights.pop(key)
    assert cache.begin(key)[0] == "lead"
    fresh = cache._flights[key]
    waiter.join(timeout=10)
    assert got == [None]
    assert cache._flights.get(key) is fresh, \
        "live flight must survive a stale waiter's timeout"


def test_stale_timeout_reaper_does_not_wake_live_waiters():
    """The reap path sets the event ONLY for the flight it actually
    popped: when finish() has already popped the flight but not yet
    published, a timed-out waiter setting done would wake every other
    waiter to result=None on a successfully COMPLETED fold (they would
    all fall through and fold again, serialized)."""
    import time

    cache = CatchupResultCache()
    key = ("e", "doc")
    assert cache.begin(key)[0] == "lead"
    flight = cache._flights[key]
    got_timeout, got_result = [], []
    stale = threading.Thread(
        target=lambda: got_timeout.append(cache.join(key, timeout=0.3)))
    live = threading.Thread(
        target=lambda: got_result.append(cache.join(key, timeout=30)))
    stale.start()
    live.start()
    time.sleep(0.1)
    # finish() preempted mid-publish: flight popped, result not yet set
    with cache._lock:
        cache._flights.pop(key)
    stale.join(timeout=10)
    assert got_timeout == [None]
    assert not flight.done.is_set(), \
        "a guard-failed reaper must not wake the leader's other waiters"
    assert not got_result, "live waiter woken before the result exists"
    # the preempted finish() resumes: publish, then wake
    flight.result = "fold-result"
    flight.done.set()
    live.join(timeout=10)
    assert got_result == ["fold-result"]


def test_catch_up_survives_crashed_leader():
    """Service-level timeout fallback: a key left in flight forever (the
    leader thread was killed before its finally-abandon) times the
    follower out, the dead flight is abandoned, and the follower folds
    the document itself — with a byte-identical result."""
    import time

    service = LocalOrderingService()
    bench.build_catchup_corpus(service, 1, 12)
    svc = CatchupService(service, mesh=None)
    svc.join_timeout = 0.2
    _summary, ref_seq, handle = service.storage.latest_with_handle("cdoc0")
    tail = service.oplog.get("cdoc0", from_seq=ref_seq)
    key = svc._cache_key("cdoc0", handle, ref_seq, tail)
    assert svc.cache.begin(key)[0] == "lead"  # the crashed leader
    t0 = time.monotonic()
    results = svc.catch_up(["cdoc0"], upload=False)
    assert time.monotonic() - t0 < 30, "follower must not hang"
    fresh = CatchupService(service, cache=None, mesh=None)
    assert results == fresh.catch_up(["cdoc0"], upload=False)
    # the dead flight was abandoned: nothing in flight, entry published,
    # so the next herd single-flights normally again
    assert svc.cache._flights == {}
    assert svc.catch_up(["cdoc0"], upload=False) == results


def test_join_timeout_config_gate(monkeypatch):
    monkeypatch.setenv("FLUID_TPU_CATCHUP_JOINTIMEOUT", "7.5")
    svc = CatchupService(LocalOrderingService(), mesh=None)
    assert svc.join_timeout == 7.5


def test_concurrent_catch_up_threads_cost_one_fold():
    """The thundering-herd contract: N concurrent catch-ups of the same
    (doc, seq) → ONE fold; the rest wait on the in-flight key and serve
    from the published entry without ever taking the device."""
    service = LocalOrderingService()
    bench.build_catchup_corpus(service, 1, 12)
    svc = CatchupService(service, mesh=None)
    folding = threading.Event()
    release = threading.Event()
    fold_calls = []
    real_fold = svc._device_fold

    def slow_fold(works):
        fold_calls.append(len(works))
        folding.set()
        assert release.wait(timeout=30)
        return real_fold(works)

    svc._device_fold = slow_fold
    results = {}

    def run(name):
        results[name] = svc.catch_up(["cdoc0"], upload=False)

    leader = threading.Thread(target=run, args=("leader",))
    leader.start()
    assert folding.wait(timeout=30)  # the key is now in flight
    waiters = [threading.Thread(target=run, args=(f"w{i}",))
               for i in range(4)]
    for t in waiters:
        t.start()
    release.set()
    leader.join(timeout=60)
    for t in waiters:
        t.join(timeout=60)
    assert fold_calls == [1], "the herd must cost exactly one fold"
    assert len({tuple(sorted(r.items())) for r in results.values()}) == 1
    assert svc.cache.counters.get("waits") >= 1


# --- tier 1 at the service: stale-store protection ---------------------------


def test_recreated_store_never_serves_stale_folds():
    """EpochTracker parity for the fold cache: a recreated (storage,
    oplog) pair carrying DIFFERENT ops at the same seq range under the
    same base summary digest must fold fresh — the old generation's
    cached tree would be byte-plausible and silently wrong."""
    service = LocalOrderingService()
    bench.build_catchup_corpus(service, 2, 10)
    svc = CatchupService(service, mesh=None)
    old = svc.catch_up(upload=False)

    # "Recreate" the store: new epoch, same doc ids, same seeded summary
    # (content-addressed → same base digest), different tail content.
    new_storage, new_oplog = SummaryStorage(), OpLog()
    service.storage, service.oplog = new_storage, new_oplog
    bench.build_catchup_corpus(service, 2, 10)
    for doc_id in ("cdoc0", "cdoc1"):
        msgs = new_oplog.get(doc_id)
        # mutate one op's text so the same seq range carries new bytes
        msgs[0].contents["ops"][0]["contents"] = {
            "kind": "insert", "pos": 0, "text": "REGENERATED",
        }
    fresh = svc.catch_up(upload=False)
    assert fresh != old, "stale fold served across a storage generation"
    for doc_id in ("cdoc0", "cdoc1"):
        assert fresh[doc_id][0] == bench.catchup_oracle_digest(
            service, doc_id)


# --- determinism: cache-on == cache-off (golden + fuzz) ----------------------


def _grow(runtimes, rng, edits=6):
    for i in range(edits):
        rt = runtimes[i % len(runtimes)]
        text = rt.get_datastore("ds").get_channel("text")
        length = len(text.text)
        if length < 4 or rng.random() < 0.7:
            text.insert_text(rng.randint(0, length), "gh"[i % 2] * 2)
        else:
            start = rng.randint(0, length - 2)
            text.remove_range(start, min(length, start + 2))
        for r in runtimes:
            r.drain()


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_cache_on_matches_cache_off(seed):
    """Across seeds and growth rounds: the cached service's results —
    cold fill, warm full hits, and suffix-extended folds — are
    byte-identical to an uncached service folding the same state."""
    import random

    service = LocalOrderingService()
    rng = random.Random(9000 + seed)
    runtimes = {
        f"doc{d}": _seed_string_doc(service, f"doc{d}",
                                    edits=6 + seed + d)
        for d in range(3)
    }
    cached = CatchupService(service, mesh=None)
    plain = CatchupService(service, mesh=None, cache=None, pack_cache=None)
    for _round in range(3):
        expect = plain.catch_up(upload=False)
        cold = cached.catch_up(upload=False)
        warm = cached.catch_up(upload=False)
        assert cold == expect, f"seed {seed}: cache-on != cache-off"
        assert warm == expect, f"seed {seed}: warm hit changed bytes"
        for rts in runtimes.values():
            _grow(rts, rng)
    # growth rounds extend tails over an unchanged base → tier 2 must
    # have reused packed windows at least once along the way
    pc = cached._pack_cache.stats()
    assert pc["suffix_hits"] + pc["exact_hits"] > 0, pc


def test_golden_corpus_cache_on_matches_cache_off():
    """Golden (pinned-workload) corpus through the service path: cached
    cold + warm results both equal the uncached fold and the container
    oracle."""
    service = LocalOrderingService()
    doc_ids = bench.build_catchup_corpus(service, 12, 20)
    cached = CatchupService(service, mesh=None)
    plain = CatchupService(service, mesh=None, cache=None, pack_cache=None)
    expect = plain.catch_up(doc_ids, upload=False)
    assert cached.catch_up(doc_ids, upload=False) == expect
    assert cached.catch_up(doc_ids, upload=False) == expect  # warm
    assert expect["cdoc0"][0] == bench.catchup_oracle_digest(
        service, "cdoc0")


# --- tier 2: pack cache -------------------------------------------------------


def _message_doc(idx: int, n_ops: int, token) -> MergeTreeDocInput:
    """A message-list (non-binary) doc over the pinned synth stream —
    the shape the catch-up service feeds the pipeline."""
    msgs = bench.doc_ops(bench.synth_doc(idx, n_ops))
    return MergeTreeDocInput(
        doc_id=f"pdoc{idx}", ops=msgs, final_seq=msgs[-1].seq,
        final_msn=0, cache_token=token,
    )


def test_pack_cache_exact_hit_reuses_chunk():
    docs = [_message_doc(i, 24, ("tok", i)) for i in range(6)]
    cache = PackCache()
    expect = [s.digest() for s in replay_mergetree_batch(docs)]
    for _pass in range(2):
        got = pipelined_mergetree_replay(docs, chunk_docs=8,
                                         pack_cache=cache)
        assert [s.digest() for s in got] == expect
    stats = cache.stats()
    assert stats["misses"] == 1 and stats["exact_hits"] == 1, stats


def test_pack_cache_suffix_extends_packed_window():
    """A tail that grew re-packs ONLY the suffix: byte-identical to a
    fresh pack of the full window, counted as a suffix hit."""
    full = [bench.doc_ops(bench.synth_doc(i, 32)) for i in range(6)]

    def window(n_ops):
        return [
            MergeTreeDocInput(
                doc_id=f"pdoc{i}", ops=msgs[:n_ops],
                final_seq=msgs[n_ops - 1].seq, final_msn=0,
                cache_token=("tok", i),
            )
            for i, msgs in enumerate(full)
        ]

    cache = PackCache()
    # 26 → 32 ops stays inside the T=32 / S=64 buckets, so the grown
    # window is suffix-extendable (the bucket-crossing case is covered
    # by test_pack_cache_bails_to_full_pack_when_buckets_grow).
    first = pipelined_mergetree_replay(window(26), chunk_docs=8,
                                       pack_cache=cache)
    assert [s.digest() for s in first] == \
        [s.digest() for s in replay_mergetree_batch(window(26))]
    grown = window(32)
    got = pipelined_mergetree_replay(grown, chunk_docs=8, pack_cache=cache)
    assert [s.digest() for s in got] == \
        [s.digest() for s in replay_mergetree_batch(grown)], (
            "suffix-extended pack changed bytes")
    stats = cache.stats()
    assert stats["suffix_hits"] == 1, stats
    # the extended window is now the cached one: an exact replay hits
    again = pipelined_mergetree_replay(grown, chunk_docs=8,
                                       pack_cache=cache)
    assert [s.digest() for s in again] == [s.digest() for s in got]
    assert cache.stats()["exact_hits"] == 1


def test_pack_cache_bails_to_full_pack_when_buckets_grow():
    """A suffix that would outgrow the chunk's op-row bucket must fall
    back to a full pack — correct bytes, counted as a miss."""
    full = [bench.doc_ops(bench.synth_doc(i, 48)) for i in range(4)]

    def window(n_ops):
        return [
            MergeTreeDocInput(
                doc_id=f"pdoc{i}", ops=msgs[:n_ops],
                final_seq=msgs[n_ops - 1].seq, final_msn=0,
                cache_token=("tok", i),
            )
            for i, msgs in enumerate(full)
        ]

    cache = PackCache()
    pipelined_mergetree_replay(window(14), chunk_docs=8, pack_cache=cache)
    grown = window(48)  # 14 → 48 text ops crosses the T=16 bucket
    got = pipelined_mergetree_replay(grown, chunk_docs=8, pack_cache=cache)
    assert [s.digest() for s in got] == \
        [s.digest() for s in replay_mergetree_batch(grown)]
    stats = cache.stats()
    assert stats["misses"] == 2 and stats["suffix_hits"] == 0, stats


def test_pack_cache_bypasses_binary_and_untokened_docs():
    cache = PackCache()
    binary = [bench.synth_doc(i, 16) for i in range(4)]  # no tokens
    got = pipelined_mergetree_replay(binary, chunk_docs=8,
                                     pack_cache=cache)
    assert [s.digest() for s in got] == \
        [s.digest() for s in replay_mergetree_batch(binary)]
    stats = cache.stats()
    assert stats["bypass"] == 1 and stats["inserts"] == 0, stats


def test_pack_cache_byte_bound_evicts():
    cache = PackCache(max_bytes=1)  # nothing fits
    docs = [_message_doc(i, 16, ("tok", i)) for i in range(4)]
    got = pipelined_mergetree_replay(docs, chunk_docs=8, pack_cache=cache)
    assert [s.digest() for s in got] == \
        [s.digest() for s in replay_mergetree_batch(docs)]
    stats = cache.stats()
    assert stats["entries"] == 0 and stats["evictions"] >= 1, stats


def test_service_growth_rides_pack_suffix_reuse():
    """Service-level tier-2: catch-up, grow the SAME docs' tails (no
    upload, so the base anchor is unchanged), catch-up again — the
    second fold must suffix-extend the cached packed window and still
    match a forced-CPU container fold byte-for-byte."""
    import random

    service = LocalOrderingService()
    runtimes = {f"doc{d}": _seed_string_doc(service, f"doc{d}", edits=8)
                for d in range(3)}
    svc = CatchupService(service, mesh=None)
    svc.catch_up(upload=False)
    rng = random.Random("suffix")
    for rts in runtimes.values():
        _grow(rts, rng, edits=5)
    cpu = CatchupService(service, cache=None, pack_cache=None)
    cpu._device_plan = lambda w: None
    expect = cpu.catch_up(upload=False)
    got = svc.catch_up(upload=False)
    assert got == expect, "suffix-reused fold != container fold"
    stats = svc._pack_cache.stats()
    assert stats["suffix_hits"] >= 1, stats
