"""Upload-side narrow transfer encoding (h2d leg of the link-bound
pipeline): ``narrow_ops_for_upload`` + in-graph ``_widen_ops`` must be an
exact round trip — the fold and export are byte-identical whether the op
stream rides the wire as int32 or as the narrowed int16/int8 layout
(BASELINE.md round-5: with the device fold at ~2 ms, e2e is host+link,
so halving the op-stream upload is a first-order lever)."""

import numpy as np
import pytest

import bench
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    MTOps,
    _UPLOAD_NARROW_DTYPES,
    export_to_numpy,
    narrow_ops_for_upload,
    pack_mergetree_batch,
    replay_export,
)
from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz
from fluidframework_tpu.testing.mocks import channel_log


def _export_bytes(state, ops, meta, S):
    ex = export_to_numpy(replay_export(state, ops, meta, S=S))
    leaves = ex if isinstance(ex, tuple) else (ex,)
    return tuple(leaf.tobytes() for leaf in leaves)


def _narrow_vs_wide(docs, monkeypatch, warm=False):
    """Pin narrow-vs-wide export byte identity through the dispatch
    path — cold by default, or the warm (base-state) path production
    ``replay_mergetree_batch`` takes for catch-up chunks."""
    state, ops, meta = pack_mergetree_batch(docs)
    S = state.tstart.shape[1]
    assert meta["i16_ok"]
    narrow = narrow_ops_for_upload(ops, meta)
    assert narrow.seq.dtype == np.int16 and narrow.kind.dtype == np.int8
    saved = sum(np.asarray(x).nbytes for x in ops) - \
        sum(np.asarray(x).nbytes for x in narrow)
    assert saved > 0
    st = state if warm else None
    # The dispatch path narrows internally; pin both encodings' bytes.
    with_narrow = _export_bytes(st, ops, meta, S)
    monkeypatch.setenv("FF_UPLOAD_NARROW", "0")
    wide = _export_bytes(st, ops, meta, S)
    assert with_narrow == wide


def test_narrow_roundtrip_on_bench_workload(monkeypatch):
    _narrow_vs_wide([bench.synth_doc(i, 48) for i in range(24)], monkeypatch)


def test_narrow_roundtrip_on_fuzz_logs(monkeypatch):
    docs = []
    for seed in (210, 211, 212):
        _r, factory = run_fuzz(StringFuzzSpec(annotate=True), seed=seed,
                               n_clients=3, rounds=8, sync_every=2)
        docs.append(MergeTreeDocInput(
            doc_id=f"n{seed}", ops=channel_log(factory, "fuzz"),
            final_seq=factory.sequencer.seq,
            final_msn=factory.sequencer.min_seq,
        ))
    _narrow_vs_wide(docs, monkeypatch)


def _warm_doc(seed, rounds=12):
    """A snapshot+tail MergeTreeDocInput: fuzz a session, summarize at
    the midpoint, return the base records + remaining tail — the
    flagship warm catch-up shape."""
    import json as _json

    from fluidframework_tpu.dds import SharedString

    _r, factory = run_fuzz(StringFuzzSpec(), seed=seed, n_clients=3,
                           rounds=rounds)
    full_ops = channel_log(factory, "fuzz")
    mid_seq = full_ops[len(full_ops) // 2].seq
    partial = SharedString("fuzz")
    for msg in full_ops:
        if msg.seq <= mid_seq:
            partial.process(msg, local=False)
    base_records = _json.loads(partial.summarize().blob_bytes("body"))
    return MergeTreeDocInput(
        doc_id=f"warm{seed}",
        ops=[m for m in full_ops if m.seq > mid_seq],
        base_records=base_records,
        final_seq=factory.sequencer.seq,
        final_msn=factory.sequencer.min_seq,
    )


def test_narrow_roundtrip_on_warm_base_state_path(monkeypatch):
    """The warm (_export_warm_fn) path: catch-up chunks with base
    summaries carry state-relative arena offsets alongside the rebased
    op tstart — the un-rebase must interact correctly with both."""
    _narrow_vs_wide([_warm_doc(s) for s in (220, 221)], monkeypatch,
                    warm=True)


def test_narrow_state_roundtrip_exact():
    """narrow_state_for_upload → _widen_state reproduces the packed base
    state array-for-array (sentinel remap + live-slot tstart rebase)."""
    import jax.numpy as jnp

    from fluidframework_tpu.ops.mergetree_kernel import (
        _widen_state,
        narrow_state_for_upload,
    )

    state, _ops, meta = pack_mergetree_batch([_warm_doc(230)])
    narrow = narrow_state_for_upload(state, meta)
    assert narrow.ins_seq.dtype == np.int16, "warm chunk should narrow"
    widened = _widen_state(narrow, jnp.asarray(meta["doc_base"]))
    for f in state._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(widened, f)), np.asarray(getattr(state, f)),
            err_msg=f)


def test_narrow_state_sentinel_collision_falls_back():
    """A genuine seq of 32767 (the remapped sentinel's value) in a
    sentinel plane must force the wide upload — narrowing it would widen
    back as NOT_REMOVED and resurrect a removed segment."""
    from fluidframework_tpu.ops.mergetree_kernel import (
        narrow_state_for_upload,
    )

    state, _ops, meta = pack_mergetree_batch([_warm_doc(231)])
    assert meta["i16_ok"]
    bad_rem = np.array(state.rem_seq)
    d = 0
    live = int(state.n[d])
    assert live > 0
    bad_rem[d, 0] = 32767  # == I16_NOT_REMOVED, but a "real" value here
    bad = state._replace(rem_seq=bad_rem)
    out = narrow_state_for_upload(bad, meta)
    assert out.rem_seq.dtype == np.int32 and out.ins_seq is bad.ins_seq


def test_widen_refuses_unknown_dtype():
    """A non-int32, non-narrow stream must be refused loudly — silently
    un-rebasing a never-rebased stream corrupts arena offsets."""
    import jax.numpy as jnp

    from fluidframework_tpu.ops.mergetree_kernel import _widen_ops

    docs = [bench.synth_doc(i, 16) for i in range(2)]
    _state, ops, _meta = pack_mergetree_batch(docs)
    # int8 seq: a dtype the narrower never emits for seq (x64 mode is
    # off, so int64 would silently truncate back to int32 here).
    bad = MTOps(*(jnp.asarray(np.asarray(x), jnp.int8)
                  if f == "seq" else jnp.asarray(np.asarray(x))
                  for f, x in zip(MTOps._fields, ops)))
    with pytest.raises(TypeError, match="seq dtype"):
        _widen_ops(bad, jnp.zeros((2,), jnp.int32))


def test_narrow_skips_non_qualifying_and_device_streams():
    docs = [bench.synth_doc(i, 32) for i in range(4)]
    state, ops, meta = pack_mergetree_batch(docs)
    # not i16_ok → identity (same objects, no copies)
    wide = narrow_ops_for_upload(ops, dict(meta, i16_ok=False))
    assert wide.seq is ops.seq
    # already-narrow stream → identity
    narrow = narrow_ops_for_upload(ops, meta)
    again = narrow_ops_for_upload(narrow, meta)
    assert again.seq is narrow.seq


def test_narrow_bounds_recheck_falls_back_to_wide():
    """A stream violating a narrow dtype's range (despite i16_ok being
    claimed) must pass through wide, never truncate."""
    docs = [bench.synth_doc(i, 32) for i in range(4)]
    _state, ops, meta = pack_mergetree_batch(docs)
    bad_client = np.array(ops.client)
    bad_client[0, 0] = 1000  # exceeds the int8 client row
    bad = ops._replace(client=bad_client)
    out = narrow_ops_for_upload(bad, meta)
    assert out.client.dtype == np.int32 and out.seq is bad.seq


def test_narrow_dtype_table_covers_every_op_field():
    assert set(_UPLOAD_NARROW_DTYPES) == set(MTOps._fields)


def test_native_widen_matches_python_widen_all_layouts():
    """oppack_widen vs widen_export: byte-identical canonical buffers on
    every transfer layout the export can emit (i16, i8 pairs, ob/ov row
    elisions, props elision, warm doc_base rebase)."""
    from fluidframework_tpu.ops.mergetree_kernel import (
        _export_flags,
        widen_export,
        widen_export_native,
    )
    from fluidframework_tpu.ops.native_pack import load_library

    if load_library() is None:
        pytest.skip("liboppack unavailable")

    cases = {
        # props-free sequential bench docs: i8 pairs + ob/ov/props elision
        "i8-elided": [bench.synth_doc(i, 48) for i in range(16)],
        # annotate-carrying docs: props rows present
        "props": [bench.synth_doc(3 * i + 1, 48) for i in range(12)],
        # warm snapshot+tail docs: doc_base rebase over base states
        "warm": [_warm_doc(240 + i) for i in range(3)],
    }
    exercised = set()
    for name, docs in cases.items():
        state, ops, meta = pack_mergetree_batch(docs)
        S = state.tstart.shape[1]
        assert meta["i16_ok"], name
        st = state if name == "warm" else None
        ex = export_to_numpy(replay_export(st, ops, meta, S=S))
        _i16, ob_f, ov_f, i8_f, props_f = _export_flags(meta)
        exercised.add((ob_f, ov_f, i8_f, props_f))
        native = widen_export_native(ex, meta.get("doc_base"), ob_f, ov_f,
                                     i8_f, meta.get("props_K"), props_f)
        assert native is not None, name
        py = widen_export(ex, meta.get("doc_base"), ob_rows=ob_f,
                          ov_rows=ov_f, i8=i8_f,
                          n_props=meta.get("props_K"), props_rows=props_f)
        np.testing.assert_array_equal(native, py, err_msg=name)
        assert native.dtype == py.dtype == np.int32
    assert len(exercised) >= 2, f"layout variety too thin: {exercised}"
    # int32 full-layout buffers must pass through to the numpy path
    state, ops, meta = pack_mergetree_batch(cases["props"])
    meta32 = dict(meta, i16_ok=False)
    ex32 = export_to_numpy(
        replay_export(None, ops, meta32, S=state.tstart.shape[1]))
    assert widen_export_native(ex32, None, True, True, False,
                               meta.get("props_K"), True) is None


def test_native_widen_rejects_malformed_desc_table():
    """oppack_widen must bounds-check the DESC table, not just ``n``
    (advisor, round 5): a ROW16 source index past R_src, a PAIR8 pair
    index past R_src, an unknown mode, or a MISC row without the misc
    output all return -1 instead of reading out of bounds."""
    import ctypes

    from fluidframework_tpu.ops.native_pack import load_library

    lib = load_library()
    if lib is None:
        pytest.skip("liboppack unavailable")
    D, S, R_src = 1, 4, 2
    src = np.zeros((D, R_src, S), np.int16)  # n (last row, col 0) = 0
    dst = np.zeros((D, 2, S), np.int32)

    def widen(desc_rows, misc=None):
        desc = np.asarray(desc_rows, np.int32).reshape(-1)
        misc_ptr = misc.ctypes.data if misc is not None else None
        misc_cols = misc.shape[1] if misc is not None else 0
        return lib.oppack_widen(
            src, D, S, R_src, len(desc_rows), misc_ptr, misc_cols, desc,
            None, 32767, 2147483647, dst,
        )

    ok = [(1, 0, 0, 0), (1, R_src - 1, 0, 0)]
    assert widen(ok) == 0  # control: a valid table still widens
    # ROW16 source index out of range (both ends)
    assert widen([(1, R_src, 0, 0), (1, 0, 0, 0)]) == -1
    assert widen([(1, -1, 0, 0), (1, 0, 0, 0)]) == -1
    # PAIR8 pair index maps past the source rows (arg/2 >= R_src)
    assert widen([(2, 2 * R_src, 0, 0), (1, 0, 0, 0)]) == -1
    assert widen([(2, -1, 0, 0), (1, 0, 0, 0)]) == -1
    # MISC row requires a non-null misc pointer
    assert widen([(3, 0, 0, 0), (1, 0, 0, 0)]) == -1
    misc = np.zeros((D, 2), np.int16)
    assert widen([(3, 0, 0, 0), (1, 0, 0, 0)], misc=misc) == 0
    # unknown mode
    assert widen([(4, 0, 0, 0), (1, 0, 0, 0)]) == -1
    assert widen([(-1, 0, 0, 0), (1, 0, 0, 0)]) == -1
