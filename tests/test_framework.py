"""Framework layer: FluidClient/FluidContainer, DataObject, DDS events,
presence (signals), undo-redo."""

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.framework import (
    ContainerSchema,
    DataObject,
    DataObjectFactory,
    FluidClient,
    Presence,
    UndoRedoStackManager,
)
from fluidframework_tpu.service import LocalOrderingService


SCHEMA = ContainerSchema(initial_objects={
    "notes": "sequence-tpu",
    "votes": "map-tpu",
    "tally": "counter-tpu",
})


def make_clients(n=2, doc_id="doc"):
    service = LocalOrderingService()
    client = FluidClient(LocalDocumentServiceFactory(service))
    first = client.create_container(doc_id, SCHEMA)
    rest = [client.get_container(doc_id, SCHEMA) for _ in range(n - 1)]
    return service, [first] + rest


def sync(containers):
    for c in containers:
        c.sync()


# --- FluidClient / FluidContainer --------------------------------------------


def test_create_and_get_container_with_initial_objects():
    _service, (a, b) = make_clients()
    assert set(a.initial_objects) == {"notes", "votes", "tally"}
    a.initial_objects["notes"].insert_text(0, "hello")
    b.initial_objects["votes"].set("q1", "yes")
    b.initial_objects["tally"].increment(3)
    sync([a, b])
    assert b.initial_objects["notes"].text == "hello"
    assert a.initial_objects["votes"].get("q1") == "yes"
    assert a.initial_objects["tally"].value == 3
    assert a.connected and b.connected


def test_dynamic_channel_creation():
    _service, (a, b) = make_clients()
    extra = a.create_channel("map-tpu", "extra")
    extra.set("k", 1)
    sync([a, b])
    b_extra = b._container.runtime.get_datastore(
        "initial-objects").get_channel("extra")
    assert b_extra.get("k") == 1


# --- DataObject ---------------------------------------------------------------


class TodoList(DataObject):
    CHANNELS = {"items": "map-tpu", "title": "cell-tpu"}

    def initialize_first_time(self):
        self.title.set("untitled")


def test_data_object_create_and_load():
    service = LocalOrderingService()
    client = FluidClient(LocalDocumentServiceFactory(service))
    a = client.create_container("doc", SCHEMA)
    factory = DataObjectFactory(TodoList)
    todo = factory.create(a._container.runtime, "todo")
    assert todo.title.get() == "untitled"
    todo.items.set("buy-milk", {"done": False})
    a.sync()

    b = client.get_container("doc", SCHEMA)
    todo_b = factory.load(b._container.runtime, "todo")
    assert todo_b.items.get("buy-milk") == {"done": False}
    assert todo_b.title.get() == "untitled"


def test_offline_dynamic_creation_survives_reconnect():
    """Datastore/channel/blob attaches made while offline must replicate
    after reconnect (review-found: they were dropped with the outbox)."""
    _service, (a, b) = make_clients()
    a._container.disconnect()
    rt = a._container.runtime
    ds = rt.create_datastore("offline-ds")
    ch = ds.create_channel("map-tpu", "data")
    ch.set("k", 42)
    blob_handle = rt.blob_manager.create_blob(b"offline-blob")
    ch.set("file", blob_handle)
    a._container.reconnect()
    sync([a, b])
    b_rt = b._container.runtime
    assert "offline-ds" in b_rt.datastores
    b_ch = b_rt.get_datastore("offline-ds").get_channel("data")
    assert b_ch.get("k") == 42
    assert b_rt.blob_manager.get_blob(b_ch.get("file")) == b"offline-blob"
    assert (rt.summarize().digest() == b_rt.summarize().digest())


def test_conflicting_channel_attach_fails_loudly():
    _service, (a, b) = make_clients()
    a._container.runtime.get_datastore("initial-objects") \
        .create_channel("map-tpu", "clash")
    b._container.runtime.get_datastore("initial-objects") \
        .create_channel("counter-tpu", "clash")
    # each side trips on the OTHER side's conflicting attach
    with pytest.raises(RuntimeError, match="conflicting channelAttach"):
        a.sync()
    with pytest.raises(RuntimeError, match="conflicting channelAttach"):
        b.sync()


# --- DDS events ---------------------------------------------------------------


def test_map_value_changed_events_local_and_remote():
    _service, (a, b) = make_clients()
    seen = []
    b.initial_objects["votes"].events.on(
        "valueChanged", lambda ev, local: seen.append((ev["key"], local)))
    a.initial_objects["votes"].set("x", 1)
    sync([a, b])
    b.initial_objects["votes"].set("y", 2)
    assert ("x", False) in seen
    assert ("y", True) in seen


def test_op_reentrancy_guard():
    _service, (a, b) = make_clients()
    votes = a.initial_objects["votes"]
    votes.events.on("valueChanged",
                    lambda ev, local: votes.set("echo", 1))
    with pytest.raises(RuntimeError, match="re-entrancy"):
        votes.set("trigger", 0)


def test_sequence_delta_events():
    _service, (a, b) = make_clients()
    deltas = []
    a.initial_objects["notes"].events.on(
        "sequenceDelta", lambda ev, local: deltas.append((ev["kind"], local)))
    a.initial_objects["notes"].insert_text(0, "abc")
    b.initial_objects["notes"].insert_text(0, "xyz")
    sync([a, b])
    assert ("insert", True) in deltas
    assert ("insert", False) in deltas


# --- presence -----------------------------------------------------------------


def test_presence_broadcast_and_late_joiner():
    service, (a, b) = make_clients()
    pa = Presence(a)
    pb = Presence(b)
    pa.workspace("cursors").set_local("pos", 17)
    assert pb.workspace("cursors").get(a.client_id, "pos") == 17
    # nothing was sequenced
    ops_before = service.oplog.head("doc")
    pa.workspace("cursors").set_local("pos", 18)
    assert service.oplog.head("doc") == ops_before
    # a late joiner requests current presence and receives it
    client = FluidClient(LocalDocumentServiceFactory(service))
    c = client.get_container("doc", SCHEMA)
    pc = Presence(c)
    assert pc.workspace("cursors").get(a.client_id, "pos") == 18


def test_presence_targeted_signal():
    _service, (a, b) = make_clients()
    got = []
    b.on_signal(lambda s: got.append(s))
    a.submit_signal({"ping": 1}, target_client_id=b.client_id)
    a.submit_signal({"ping": 2}, target_client_id="someone-else")
    pings = [s["content"]["ping"] for s in got
             if s.get("targetClientId") in (b.client_id, None)
             and "ping" in (s.get("content") or {})]
    assert 1 in pings and 2 not in pings


# --- undo-redo ----------------------------------------------------------------


def test_undo_redo_map_and_counter():
    _service, (a, b) = make_clients()
    mgr = UndoRedoStackManager()
    votes, tally = a.initial_objects["votes"], a.initial_objects["tally"]
    mgr.attach(votes)
    mgr.attach(tally)

    votes.set("k", "v1")
    votes.set("k", "v2")
    tally.increment(5)
    sync([a, b])

    assert mgr.undo()  # undo increment
    sync([a, b])
    assert tally.value == 0
    assert b.initial_objects["tally"].value == 0

    assert mgr.undo()  # undo k=v2
    sync([a, b])
    assert votes.get("k") == "v1"

    assert mgr.redo()
    sync([a, b])
    assert votes.get("k") == "v2"
    assert b.initial_objects["votes"].get("k") == "v2"


def test_undo_string_insert_and_remove():
    _service, (a, b) = make_clients()
    mgr = UndoRedoStackManager()
    notes = a.initial_objects["notes"]
    mgr.attach(notes)

    notes.insert_text(0, "hello world")
    notes.remove_range(5, 11)  # "hello"
    sync([a, b])
    assert notes.text == "hello"

    assert mgr.undo()  # restore " world"
    sync([a, b])
    assert notes.text == "hello world"
    assert b.initial_objects["notes"].text == "hello world"

    assert mgr.undo()  # remove the original insert
    sync([a, b])
    assert notes.text == ""

    assert mgr.redo()
    sync([a, b])
    assert notes.text == "hello world"


def test_undo_grouped_operation():
    _service, (a, b) = make_clients()
    mgr = UndoRedoStackManager()
    votes = a.initial_objects["votes"]
    mgr.attach(votes)
    with mgr.operation():
        votes.set("a", 1)
        votes.set("b", 2)
        votes.set("c", 3)
    sync([a, b])
    assert mgr.undo()  # one step reverts all three
    sync([a, b])
    assert votes.get("a") is None and votes.get("c") is None
    assert not mgr.can_undo


def test_undo_merges_with_concurrent_remote_edit():
    _service, (a, b) = make_clients()
    mgr = UndoRedoStackManager()
    notes_a = a.initial_objects["notes"]
    notes_b = b.initial_objects["notes"]
    mgr.attach(notes_a)
    notes_a.insert_text(0, "AAA ")
    sync([a, b])
    notes_b.insert_text(4, "BBB ")
    sync([a, b])
    assert notes_a.text == "AAA BBB "
    mgr.undo()  # removes "AAA " — BBB survives
    sync([a, b])
    assert notes_a.text == notes_b.text == "BBB "
