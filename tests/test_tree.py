"""SharedTree oracle tests: convergence, summaries, transactions, schema.

Mirrors the reference's tree test strategy (SURVEY.md §4): multi-client
mock-runtime scenarios with controlled interleavings, plus a seeded
mini-fuzz convergence loop.
"""

import random

import pytest

from fluidframework_tpu.dds.tree import (
    FIELD_START,
    ROOT_ID,
    SchemaFactory,
    SharedTree,
    TreeViewConfiguration,
    compose,
    invert,
)
from fluidframework_tpu.testing.mocks import MockContainerRuntimeFactory


def make_clients(n, config=None):
    factory = MockContainerRuntimeFactory()
    trees = []
    for i in range(n):
        rt = factory.create_client(f"client{i}")
        trees.append(rt.attach(SharedTree("tree", config=config)))
    return factory, trees


def assert_converged(trees):
    objs = [t.to_obj() for t in trees]
    digests = [t.summarize().digest() for t in trees]
    for o in objs[1:]:
        assert o == objs[0]
    for d in digests[1:]:
        assert d == digests[0]


# -- basics -----------------------------------------------------------------


def test_detached_insert_and_read():
    t = SharedTree("t")
    ids = t.insert(ROOT_ID, "items", 0, [t.build("note", value="hello")])
    assert t.children(ROOT_ID, "items") == ids
    assert t.value_of(ids[0]) == "hello"
    assert t.type_of(ids[0]) == "note"


def test_nested_content_materializes():
    t = SharedTree("t")
    spec = t.build(
        "list", fields={"rows": [t.build("row", value=1),
                                 t.build("row", value=2)]}
    )
    (lid,) = t.insert(ROOT_ID, "", 0, [spec])
    rows = t.children(lid, "rows")
    assert [t.value_of(r) for r in rows] == [1, 2]


def test_two_clients_basic_convergence():
    factory, (a, b) = make_clients(2)
    a.insert(ROOT_ID, "items", 0, [a.build("n", value="from-a")])
    b.insert(ROOT_ID, "items", 0, [b.build("n", value="from-b")])
    factory.process_all_messages()
    assert_converged([a, b])
    # Both inserted at index 0 concurrently: newest-first means the
    # later-sequenced block (b's, submitted second) lands at the start.
    vals = [a.value_of(c) for c in a.children(ROOT_ID, "items")]
    assert sorted(vals) == ["from-a", "from-b"]


def test_same_anchor_concurrent_inserts_stack_newest_first():
    factory, (a, b) = make_clients(2)
    (base,) = a.insert(ROOT_ID, "s", 0, [a.build("n", value="base")])
    factory.process_all_messages()
    # Both now insert at index 1 (after base) concurrently.
    a.insert(ROOT_ID, "s", 1, [a.build("n", value="a1")])
    b.insert(ROOT_ID, "s", 1, [b.build("n", value="b1")])
    factory.process_all_messages()
    assert_converged([a, b])
    vals = [a.value_of(c) for c in a.children(ROOT_ID, "s")]
    # b's op sequenced later -> newer -> nearer the anchor.
    assert vals == ["base", "b1", "a1"]


def test_remove_and_tombstone_anchor():
    factory, (a, b) = make_clients(2)
    ids = a.insert(ROOT_ID, "s", 0, [
        a.build("n", value=i) for i in range(3)
    ])
    factory.process_all_messages()
    # a removes the middle node; b concurrently inserts after it.
    a.remove(ids[1])
    b.insert(ROOT_ID, "s", 2, [b.build("n", value="x")])
    factory.process_all_messages()
    assert_converged([a, b])
    vals = [a.value_of(c) for c in a.children(ROOT_ID, "s")]
    # b anchored at the removed node; the tombstone keeps the position.
    assert vals == [0, "x", 2]


def test_insert_under_concurrently_removed_ancestor():
    factory, (a, b) = make_clients(2)
    (box,) = a.insert(ROOT_ID, "", 0, [a.build("box")])
    factory.process_all_messages()
    a.remove(box)
    b.insert(box, "items", 0, [b.build("n", value="orphan")])
    factory.process_all_messages()
    assert_converged([a, b])
    assert a.children(ROOT_ID, "") == []  # box gone, orphan invisible


def test_value_lww_and_pending_hold():
    factory, (a, b) = make_clients(2)
    (nid,) = a.insert(ROOT_ID, "", 0, [a.build("n", value=0)])
    factory.process_all_messages()
    a.set_value(nid, "from-a")
    b.set_value(nid, "from-b")
    # Before sequencing each sees its own pending value.
    assert a.value_of(nid) == "from-a"
    assert b.value_of(nid) == "from-b"
    factory.process_all_messages()
    assert_converged([a, b])
    # b submitted second -> sequenced later -> wins LWW.
    assert a.value_of(nid) == "from-b"


def test_concurrent_remove_remove():
    factory, (a, b) = make_clients(2)
    (nid,) = a.insert(ROOT_ID, "", 0, [a.build("n")])
    factory.process_all_messages()
    a.remove(nid)
    b.remove(nid)
    factory.process_all_messages()
    assert_converged([a, b])
    assert not a.contains(nid)


# -- move -------------------------------------------------------------------


def test_move_basic():
    factory, (a, b) = make_clients(2)
    ids = a.insert(ROOT_ID, "s", 0, [a.build("n", value=i) for i in range(3)])
    factory.process_all_messages()
    a.move([ids[0]], ROOT_ID, "s", 3)
    factory.process_all_messages()
    assert_converged([a, b])
    vals = [a.value_of(c) for c in a.children(ROOT_ID, "s")]
    assert vals == [1, 2, 0]


def test_move_vs_concurrent_remove_remove_wins():
    factory, (a, b) = make_clients(2)
    (box,) = a.insert(ROOT_ID, "", 0, [a.build("box")])
    (nid,) = a.insert(ROOT_ID, "loose", 0, [a.build("n", value="m")])
    factory.process_all_messages()
    a.remove(nid)
    b.move([nid], box, "kept", 0)
    factory.process_all_messages()
    assert_converged([a, b])
    assert a.children(box, "kept") == []


def test_concurrent_cross_moves_no_cycle():
    factory, (a, b) = make_clients(2)
    (x,) = a.insert(ROOT_ID, "", 0, [a.build("x")])
    (y,) = a.insert(ROOT_ID, "", 1, [a.build("y")])
    factory.process_all_messages()
    a.move([x], y, "kids", 0)
    b.move([y], x, "kids", 0)
    factory.process_all_messages()
    assert_converged([a, b])
    # One move won (the earlier-sequenced), the other was dropped.
    top = a.children(ROOT_ID, "")
    assert len(top) == 1


# -- transactions & undo ----------------------------------------------------


def test_transaction_is_atomic_remotely():
    factory, (a, b) = make_clients(2)
    with a.transaction():
        (lid,) = a.insert(ROOT_ID, "", 0, [a.build("list")])
        a.insert(lid, "rows", 0, [a.build("row", value=1)])
        a.insert(lid, "rows", 1, [a.build("row", value=2)])
    assert factory.pending_count == 1  # one composed op on the wire
    factory.process_all_messages()
    assert_converged([a, b])
    (lid_b,) = b.children(ROOT_ID, "")
    assert [b.value_of(r) for r in b.children(lid_b, "rows")] == [1, 2]


def test_transaction_abort_rolls_back():
    factory, (a, b) = make_clients(2)
    (nid,) = a.insert(ROOT_ID, "", 0, [a.build("n", value="keep")])
    factory.process_all_messages()
    before = a.to_obj()
    with pytest.raises(RuntimeError):
        with a.transaction():
            a.insert(ROOT_ID, "", 1, [a.build("n", value="bye")])
            a.set_value(nid, "changed")
            a.remove(nid)
            raise RuntimeError("abort")
    assert a.to_obj() == before
    assert factory.pending_count == 0
    factory.process_all_messages()
    assert_converged([a, b])


def test_undo_remove_revives():
    factory, (a, b) = make_clients(2)
    (nid,) = a.insert(ROOT_ID, "", 0, [a.build("n", value="v")])
    factory.process_all_messages()
    cs = {"edits": [{"kind": "remove", "ids": [nid]}]}
    a.remove(nid)
    factory.process_all_messages()
    a.undo_changeset(cs)
    factory.process_all_messages()
    assert_converged([a, b])
    assert b.contains(nid)
    assert b.value_of(nid) == "v"


def test_undo_insert_removes():
    factory, (a, b) = make_clients(2)
    ids = a.insert(ROOT_ID, "", 0, [a.build("n", value="v")])
    factory.process_all_messages()
    # Reconstruct the changeset that inserted (from the trunk tail).
    seq, client, changeset = a.edit_manager.trunk[-1]
    a.undo_changeset(changeset)
    factory.process_all_messages()
    assert_converged([a, b])
    assert not a.contains(ids[0])


# -- summaries & catch-up ---------------------------------------------------


def test_summary_roundtrip_and_catchup():
    factory, (a, b) = make_clients(2)
    ids = a.insert(ROOT_ID, "s", 0, [a.build("n", value=i) for i in range(4)])
    factory.process_all_messages()
    summary = a.summarize()
    # A fresh replica loads the summary, then replays the tail.
    c_rt = factory.create_client("client2")
    c = SharedTree("tree2")
    c.load(summary)
    assert c.to_obj() == a.to_obj()
    assert c.summarize().digest() == a.summarize().digest()


def test_summary_normalizes_pending_state():
    factory, (a, b) = make_clients(2)
    a.insert(ROOT_ID, "", 0, [a.build("n", value="sequenced")])
    factory.process_all_messages()
    d0 = a.summarize().digest()
    a.insert(ROOT_ID, "", 0, [a.build("n", value="pending")])
    assert a.summarize().digest() == d0  # pending excluded
    factory.process_all_messages()
    assert a.summarize().digest() != d0


def test_zamboni_purges_expired_tombstones():
    factory, (a, b) = make_clients(2)
    ids = a.insert(ROOT_ID, "", 0, [a.build("n", value=i) for i in range(3)])
    factory.process_all_messages()
    a.remove(ids[1])
    factory.process_all_messages()
    assert a.seq_forest.contains(ids[1])  # tombstone inside the window
    factory.advance_min_seq()
    assert not a.seq_forest.contains(ids[1])  # purged
    assert not b.seq_forest.contains(ids[1])
    assert_converged([a, b])


def test_summary_clamps_below_min_seq():
    """Replicas whose histories differ only below min_seq emit identical
    bytes (the merge-tree normalization property, SEMANTICS.md)."""
    factory, (a, b) = make_clients(2)
    a.insert(ROOT_ID, "", 0, [a.build("n", value="x")])
    factory.process_all_messages()
    factory.advance_min_seq()
    fresh = SharedTree("f")
    fresh.load(a.summarize())
    assert fresh.summarize().digest() == a.summarize().digest()


def test_undo_after_purge_keeps_removed_descendants_hidden():
    """Repair content must not resurrect descendants removed by other edits
    (review-found): remove child, remove ancestor, purge, undo the ancestor
    removal — the child stays hidden on every replica."""
    factory, (a, b) = make_clients(2)
    (box,) = a.insert(ROOT_ID, "", 0, [a.build("box")])
    (child,) = a.insert(box, "kids", 0, [a.build("n", value="c")])
    factory.process_all_messages()
    a.remove(child)
    factory.process_all_messages()
    a.remove(box)
    factory.process_all_messages()
    seq, client, remove_box_cs = a.edit_manager.trunk[-1]
    inverse = invert(remove_box_cs, a.seq_forest)  # capture before purge
    factory.advance_min_seq()  # purges both tombstones
    assert not a.seq_forest.contains(box)
    a._submit_changeset(inverse)
    factory.process_all_messages()
    assert_converged([a, b])
    assert a.contains(box)
    assert not a.contains(child)
    assert a.children(box, "kids") == []


def test_catchup_tail_overlap_is_idempotent():
    """A replayed tail that overlaps the loaded summary must not
    double-apply (review-found): the summary header carries its sequence
    point and older ops are skipped."""
    from fluidframework_tpu.testing.mocks import channel_log

    factory, (a, b) = make_clients(2)
    a.insert(ROOT_ID, "", 0, [a.build("n", value=1)])
    factory.process_all_messages()
    summary = a.summarize()
    b.insert(ROOT_ID, "", 1, [b.build("n", value=2)])
    factory.process_all_messages()
    fresh = SharedTree("tree")
    fresh.load(summary)
    # Replay the FULL log, including ops already folded into the summary.
    for msg in channel_log(factory, "tree"):
        fresh.process(msg, local=False)
    assert fresh.to_obj() == a.to_obj()
    assert fresh.summarize().digest() == a.summarize().digest()


# -- schema -----------------------------------------------------------------


def test_schema_allows_and_rejects():
    sf = SchemaFactory("app")
    note = sf.object("note", {"title": sf.value()})
    board = sf.object("board", {"notes": sf.sequence("app.note")})
    config = TreeViewConfiguration(schema=sf, root_allowed=("app.board",))
    t = SharedTree("t", config=config)
    (bid,) = t.insert(ROOT_ID, "", 0, [t.build("app.board")])
    t.insert(bid, "notes", 0, [t.build("app.note")])
    with pytest.raises(ValueError):
        t.insert(bid, "notes", 0, [t.build("app.board")])
    with pytest.raises(ValueError):
        t.insert(bid, "bogus_field", 0, [t.build("app.note")])
    with pytest.raises(ValueError):
        t.insert(ROOT_ID, "", 1, [t.build("app.note")])


# -- reconnect / resubmit ---------------------------------------------------


def test_changeset_algebra_compose_invert():
    t = SharedTree("t")
    (nid,) = t.insert(ROOT_ID, "", 0, [t.build("n", value=1)])
    cs = {"edits": [{"kind": "set", "id": nid, "value": 2, "prev": 1}]}
    inv = invert(cs, t.seq_forest)
    assert inv["edits"][0]["value"] == 1
    both = compose([cs, inv])
    assert len(both["edits"]) == 2


# -- mini-fuzz --------------------------------------------------------------


@pytest.mark.parametrize("seed", [7, 21, 99, 123, 4242])
def test_fuzz_convergence(seed):
    rng = random.Random(seed)
    factory, trees = make_clients(3)
    for step in range(120):
        t = rng.choice(trees)
        roll = rng.random()
        try:
            if roll < 0.45:
                field = rng.choice(["a", "b"])
                kids = t.children(ROOT_ID, field)
                idx = rng.randint(0, len(kids))
                t.insert(ROOT_ID, field, idx,
                         [t.build("n", value=rng.randint(0, 99))])
            elif roll < 0.6:
                field = rng.choice(["a", "b"])
                kids = t.children(ROOT_ID, field)
                if kids:
                    t.remove(rng.choice(kids))
            elif roll < 0.75:
                field = rng.choice(["a", "b"])
                kids = t.children(ROOT_ID, field)
                if kids:
                    t.set_value(rng.choice(kids), rng.randint(0, 99))
            elif roll < 0.9:
                src = rng.choice(["a", "b"])
                dst = rng.choice(["a", "b"])
                kids = t.children(ROOT_ID, src)
                if kids:
                    nid = rng.choice(kids)
                    dst_kids = [
                        k for k in t.children(ROOT_ID, dst) if k != nid
                    ]
                    t.move([nid], ROOT_ID, dst,
                           rng.randint(0, len(dst_kids)))
            else:
                factory.process_some_messages(rng.randint(1, 5))
        except (KeyError, ValueError):
            pass  # raced against own pending state; fine for fuzz
    factory.process_all_messages()
    assert_converged(trees)
    factory.advance_min_seq()
    assert_converged(trees)
    # Summary round-trip equivalence after the run.
    fresh = SharedTree("f")
    fresh.load(trees[0].summarize())
    assert fresh.summarize().digest() == trees[0].summarize().digest()
