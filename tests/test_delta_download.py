"""Digest-gated incremental export (ISSUE 6): tier 0 of the catch-up
cache.  The fold emits a per-doc state digest on device; a warm catch-up
over a grown tail downloads + extracts ONLY the changed documents' export
rows, serving unchanged documents' cached summaries byte-identically.

Pinned here: golden + fuzz byte identity (delta-on == delta-off == the
one-batch replay) across grown tails, the forced-digest-mismatch and
cold-start fallback routes, epoch invalidation, the tier-0 LRU/byte
bounds, the honest ``device_wait``/``download``/``d2h_bytes`` stage
split, and the deterministic ≥5× d2h byte drop on a warm grown-tail run
(a byte-counter gate — it cannot flake on scheduler noise)."""

import random

import pytest

import bench
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    replay_mergetree_batch,
)
from fluidframework_tpu.ops.pipeline import (
    PackCache,
    pipelined_mergetree_replay,
)
from fluidframework_tpu.service.catchup_cache import DeltaExportCache


def _streams(n_docs, n_ops=32):
    return [bench.doc_ops(bench.synth_doc(i, n_ops)) for i in range(n_docs)]


def _window(streams, i, n_ops, epoch="ep"):
    msgs = streams[i][:n_ops]
    return MergeTreeDocInput(
        doc_id=f"d{i}", ops=msgs, final_seq=msgs[-1].seq, final_msn=0,
        cache_token=(epoch, f"d{i}", 0, ""),
    )


def _corpus(streams, grown=(), lo=26, hi=32, epoch="ep"):
    # 26 → 32 ops stays inside the T=32 / S=64 buckets, so grown windows
    # ride the pack cache's suffix path (the bucket-crossing repack case
    # is exercised by the fuzz test's larger growth).
    return [
        _window(streams, i, hi if i in grown else lo, epoch)
        for i in range(len(streams))
    ]


def _run(docs, delta, pack, **kw):
    stage: dict = {}
    stats: dict = {}
    out = pipelined_mergetree_replay(
        docs, chunk_docs=kw.pop("chunk_docs", 8), delta_cache=delta,
        pack_cache=pack, stage=stage, stats=stats, **kw)
    return [s.digest() for s in out], stage, stats


# --- golden byte identity ----------------------------------------------------


def test_delta_download_golden_byte_identity():
    """Cold fill, then a warm grown-tail pass: delta-download summaries
    are byte-identical to the one-batch full replay; unchanged docs are
    served without download and the d2h byte counter drops."""
    streams = _streams(12)
    delta, pack = DeltaExportCache(), PackCache()
    cold_docs = _corpus(streams)
    got, stage_cold, _ = _run(cold_docs, delta, pack)
    assert got == [s.digest() for s in replay_mergetree_batch(cold_docs)]
    assert stage_cold["d2h_bytes"] > 0
    assert "device_wait" in stage_cold and "download" in stage_cold

    grown = _corpus(streams, grown={0, 5})
    got, stage_warm, stats = _run(grown, delta, pack)
    assert got == [s.digest() for s in replay_mergetree_batch(grown)], (
        "delta-download changed bytes on a grown tail"
    )
    assert stats.get("delta_docs", 0) == 10, stats
    assert delta.stats()["served"] == 10
    assert stage_warm["d2h_bytes"] < stage_cold["d2h_bytes"]


def test_delta_all_unchanged_serves_without_rows():
    """A byte-identical re-run downloads only the digest plane: every
    document serves from tier 0, zero extraction."""
    streams = _streams(10)
    delta, pack = DeltaExportCache(), PackCache()
    docs = _corpus(streams)
    expect, stage_cold, _ = _run(docs, delta, pack)
    again, stage_warm, stats = _run(docs, delta, pack)
    assert again == expect
    assert stats.get("delta_docs", 0) == len(docs)
    # Only the [D, 2] int32 digest plane crossed: 8 bytes per doc.
    assert stage_warm["d2h_bytes"] == 8 * len(docs)
    assert stage_warm.get("extract", 0.0) == 0.0


def test_cold_start_without_cache_is_the_full_path():
    """delta_cache=None keeps the existing full-fetch pipeline exactly
    (the fallback route IS the golden oracle)."""
    streams = _streams(8)
    docs = _corpus(streams)
    got, stage, stats = _run(docs, None, None)
    assert got == [s.digest() for s in replay_mergetree_batch(docs)]
    assert "delta_docs" not in stats
    assert stage["d2h_bytes"] > 0


# --- fallback routes ---------------------------------------------------------


def test_forced_digest_mismatch_falls_back_to_download():
    """A corrupted tier-0 digest must fall back to the full row fetch for
    that document — counted as ``changed``, bytes still identical."""
    streams = _streams(9)
    delta, pack = DeltaExportCache(), PackCache()
    docs = _corpus(streams)
    expect, _, _ = _run(docs, delta, pack)
    # Poison one entry's digest (simulates any digest drift).
    with delta._lock:
        token = docs[3].cache_token
        entry = delta._entries[token]
        delta._entries[token] = entry._replace(digest=(1, 2))
    again, _, stats = _run(docs, delta, pack)
    assert again == expect, "digest-mismatch fallback changed bytes"
    assert stats.get("delta_docs", 0) == len(docs) - 1
    st = delta.stats()
    assert st["changed"] == 1, st
    # ...and the fallback re-published the true digest: a third run
    # serves everything again.
    final, _, stats3 = _run(docs, delta, pack)
    assert final == expect
    assert stats3.get("delta_docs", 0) == len(docs)


def test_epoch_bump_invalidates_tier0():
    """Entries are keyed by the storage epoch (token component 0): a new
    generation can never be served stale summaries, and eager
    invalidation frees the budget."""
    streams = _streams(6)
    delta, pack = DeltaExportCache(), PackCache()
    _run(_corpus(streams, epoch="e1"), delta, pack)
    assert len(delta) == 6
    assert delta.invalidate_epoch("e2") == 6
    assert len(delta) == 0
    assert delta.stats()["invalidations"] == 6
    # New-generation tokens fold full (no serves) and stay byte-correct.
    docs2 = _corpus(streams, epoch="e2")
    got, _, stats = _run(docs2, delta, pack)
    assert got == [s.digest() for s in replay_mergetree_batch(docs2)]
    assert stats.get("delta_docs", 0) == 0


# --- tier-0 cache unit behavior ----------------------------------------------


def test_tier0_anchor_guards_host_side_inputs():
    """Same token + same device digest but a changed host anchor (an
    extraction input the digest cannot see — final_msn here) must MISS:
    the cached summary's header/expiry would be wrong."""
    streams = _streams(4)
    delta, pack = DeltaExportCache(), PackCache()
    docs = _corpus(streams)
    _run(docs, delta, pack)
    moved = [
        MergeTreeDocInput(
            doc_id=d.doc_id, ops=d.ops, final_seq=d.final_seq,
            final_msn=d.final_msn + 1, cache_token=d.cache_token)
        for d in docs
    ]
    got, _, stats = _run(moved, delta, pack)
    assert got == [s.digest() for s in replay_mergetree_batch(moved)]
    assert stats.get("delta_docs", 0) == 0, (
        "anchor drift must not serve cached summaries"
    )


def test_tier0_bypasses_binary_and_tokenless_docs():
    delta = DeltaExportCache()
    binary = bench.synth_doc(0, 16)  # binary stream, no token
    tokenless = MergeTreeDocInput(
        doc_id="t", ops=bench.doc_ops(bench.synth_doc(1, 8)),
        final_seq=8, final_msn=0)
    for doc in (binary, tokenless):
        assert not delta.candidate(doc)
        assert delta.serve(doc, (0, 0)) is None
        delta.put(doc, (0, 0), replay_mergetree_batch([doc])[0])
    assert len(delta) == 0


def test_tier0_byte_bound_and_lru_eviction():
    from fluidframework_tpu.protocol.summary import SummaryTree
    from fluidframework_tpu.service.catchup_cache import tree_nbytes

    def blob(n):
        t = SummaryTree()
        t.add_blob("body", b"x" * n)
        return t

    def doc(i):
        return MergeTreeDocInput(
            doc_id=f"d{i}", ops=bench.doc_ops(bench.synth_doc(i, 4)),
            final_seq=4, final_msn=0, cache_token=("e", i))

    one = tree_nbytes(blob(1000))
    cache = DeltaExportCache(max_bytes=3 * one)
    for i in range(3):
        cache.put(doc(i), (i, i), blob(1000))
    assert len(cache) == 3
    # Touch d0 (serve) so d1 is least-recent, then overflow by one.
    assert cache.serve(doc(0), (0, 0)) is not None
    cache.put(doc(3), (3, 3), blob(1000))
    assert cache.serve(doc(1), (1, 1)) is None, "LRU must evict d1 first"
    assert cache.serve(doc(0), (0, 0)) is not None
    st = cache.stats()
    assert st["evictions"] == 1 and st["bytes"] <= cache.max_bytes
    # An entry larger than the whole budget is never admitted.
    big = DeltaExportCache(max_bytes=400)
    big.put(doc(0), (0, 0), blob(10))
    big.put(doc(1), (1, 1), blob(10_000))
    assert big.serve(doc(1), (1, 1)) is None
    assert big.serve(doc(0), (0, 0)) is not None


def test_digest_invariant_to_props_K_bucket_growth():
    """Another document introducing NEW annotate keys grows the chunk's
    props-K bucket.  An unchanged document's digest must not move (absent
    keys hash zero) — else every K growth silently degrades tier 0 to
    full download across the whole chunk."""
    import numpy as np

    from fluidframework_tpu.ops.mergetree_kernel import (
        replay_export,
        split_export_digest,
    )
    from fluidframework_tpu.ops.pipeline import PackCache
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def msg(seq, contents):
        return SequencedMessage(
            seq=seq, client_id="c0", client_seq=seq, ref_seq=seq - 1,
            min_seq=0, type=MessageType.OP, contents=contents)

    def annotated_doc(doc_id, keys):
        ops = [msg(1, {"kind": "insert", "pos": 0, "text": "stable txt"})]
        for i, key in enumerate(keys):
            ops.append(msg(2 + i, {"kind": "annotate", "start": 0,
                                   "end": 4, "props": {key: 1}}))
        return MergeTreeDocInput(
            doc_id=doc_id, ops=ops, final_seq=len(ops), final_msn=0,
            cache_token=("ep", doc_id, 0, ""))

    def digest_of(docs, want_id):
        state, ops, meta = PackCache().pack(docs)
        core, dig = split_export_digest(
            replay_export(state, ops, meta, digest=True), True)
        dig_np = np.asarray(dig)
        d = [x.doc_id for x in meta["docs"]].index(want_id)
        return (int(dig_np[d, 0]), int(dig_np[d, 1]))

    a = annotated_doc("A", ["f"])
    with_k1 = digest_of([a, annotated_doc("B", ["f"])], "A")
    with_k3 = digest_of([a, annotated_doc("B", ["f", "g", "h"])], "A")
    assert with_k1 == with_k3, (
        "unchanged doc's digest moved when the chunk's K bucket grew"
    )
    # ...while a SET value must stay distinct from absent even for the
    # first-interned value id 0 (the +1 shift): same segments, same cols,
    # only the props plane differs — a full-segment annotate never splits.
    plain = MergeTreeDocInput(
        doc_id="A", ops=[msg(1, {"kind": "insert", "pos": 0,
                                 "text": "stable txt"})],
        final_seq=1, final_msn=0, cache_token=("ep", "A", 0, ""))
    full_ann = MergeTreeDocInput(
        doc_id="A",
        ops=plain.ops + [msg(2, {"kind": "annotate", "start": 0,
                                 "end": 10, "props": {"f": 1}})],
        final_seq=2, final_msn=0, cache_token=("ep", "A", 0, ""))
    assert digest_of([plain], "A") != digest_of([full_ann], "A"), (
        "value id 0 aliased with absent — the +1 shift is not applied"
    )


def test_gather_device_path_matches_host_view(monkeypatch):
    """``gather_export_rows`` has two legs: the zero-copy host view (CPU
    buffers) and the jitted device gather (accelerators).  CPU CI always
    takes the first — force the second and pin byte parity, so the
    accelerator leg cannot rot unexercised."""
    import numpy as np

    from fluidframework_tpu.ops import mergetree_kernel as mk

    streams = _streams(6)
    delta, pack = DeltaExportCache(), PackCache()
    docs = _corpus(streams)
    expect, _, _ = _run(docs, delta, pack)
    grown = _corpus(streams, grown={1, 4})
    via_host, _, _ = _run(grown, DeltaExportCache(), PackCache())
    # Fill a fresh tier 0, then serve the same grown corpus with the
    # host view disabled: the device gather must produce the same bytes.
    delta2, pack2 = DeltaExportCache(), PackCache()
    _run(docs, delta2, pack2)
    # The helper on the host leg first: exact rows, exact byte count.
    a = mk.jnp.arange(120, dtype=mk.jnp.int16).reshape(30, 4)
    idx = np.asarray([2, 7, 19], np.int32)
    host_rows, host_moved = mk.gather_export_rows(a, idx)
    assert host_rows.shape == (3, 4) and host_moved == host_rows.nbytes
    monkeypatch.setattr(mk, "_host_view", lambda a: None)
    via_dev, _, stats = _run(grown, delta2, pack2)
    assert via_dev == via_host
    assert stats.get("delta_docs", 0) == len(docs) - 2
    # Device leg: same rows; the internal fine-bucket pad rows count as
    # moved bytes (they really cross the link on an accelerator).
    dev_rows, dev_moved = mk.gather_export_rows(a, idx)
    assert np.array_equal(dev_rows, np.asarray(host_rows))
    assert dev_moved >= host_moved
    assert dev_moved == 8 * a[0].nbytes  # next_bucket_fine(3, floor=8)


# --- fuzz: delta-on == delta-off across random growth ------------------------


@pytest.mark.parametrize("seed", range(3))
def test_fuzz_delta_on_matches_delta_off(seed):
    """Random growth rounds (including bucket-crossing repacks and
    interval/annotate docs): every round's delta-served results equal a
    fresh full replay byte-for-byte."""
    from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz
    from fluidframework_tpu.testing.mocks import channel_log

    rng = random.Random(7700 + seed)
    streams = _streams(8, n_ops=48)
    fuzz_docs = []
    for i, spec in enumerate((StringFuzzSpec(annotate=True,
                                             intervals=True),
                              StringFuzzSpec(obliterate=True))):
        _r, f = run_fuzz(spec, seed=7800 + 10 * seed + i, n_clients=3,
                         rounds=6, sync_every=2)
        fuzz_docs.append(MergeTreeDocInput(
            doc_id=f"fz{i}", ops=channel_log(f, "fuzz"),
            final_seq=f.sequencer.seq, final_msn=f.sequencer.min_seq,
            cache_token=("ep", f"fz{i}", 0, "")))
    delta, pack = DeltaExportCache(), PackCache()
    windows = [12] * len(streams)
    served_total = 0
    for _round in range(4):
        docs = [_window(streams, i, windows[i])
                for i in range(len(streams))] + fuzz_docs
        expect = [s.digest() for s in replay_mergetree_batch(docs)]
        got, _, stats = _run(docs, delta, pack, chunk_docs=6)
        assert got == expect, f"seed {seed}: delta-on != full replay"
        served_total += stats.get("delta_docs", 0)
        for i in range(len(streams)):  # grow a random subset
            if rng.random() < 0.4:
                windows[i] = min(len(streams[i]),
                                 windows[i] + rng.randint(1, 14))
    assert served_total > 0, "fuzz never exercised the delta serve path"


# --- the perf gate: bytes, not seconds ---------------------------------------


def test_warm_grown_tail_fetches_5x_fewer_bytes():
    """The acceptance gate, on deterministic byte counters: a warm
    grown-tail run (1/16 of documents grew) moves ≥5× fewer d2h bytes
    than the full-download path over the same corpus."""
    streams = _streams(128)
    delta, pack = DeltaExportCache(), PackCache()
    cold = _corpus(streams)
    _run(cold, delta, pack, chunk_docs=64)
    grown_idx = set(range(0, 128, 16))
    grown = _corpus(streams, grown=grown_idx)
    got_delta, stage_delta, stats = _run(grown, delta, pack,
                                         chunk_docs=64)
    got_full, stage_full, _ = _run(grown, None, None, chunk_docs=64)
    assert got_delta == got_full, "delta and full runs disagree"
    assert stats.get("delta_docs", 0) == 128 - len(grown_idx)
    assert stage_delta["d2h_bytes"] * 5 <= stage_full["d2h_bytes"], (
        f"delta fetched {stage_delta['d2h_bytes']} B vs full "
        f"{stage_full['d2h_bytes']} B — less than the 5x floor"
    )
    assert delta.stats()["bytes_saved"] > 0


# --- service level -----------------------------------------------------------


def test_service_tier0_serves_when_tier1_is_off():
    """With tier 1 disabled (as after an eviction/restart of the result
    cache), a repeated catch-up re-folds — and tier 0 serves every
    unchanged string channel without a download, byte-identically."""
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService

    service = LocalOrderingService()
    doc_ids = bench.build_catchup_corpus(service, 6, 14)
    svc = CatchupService(service, mesh=None, cache=None)
    assert svc.delta_cache is not None, "gate must default on"
    plain = CatchupService(service, mesh=None, cache=None,
                           pack_cache=None, delta_cache=None)
    expect = plain.catch_up(doc_ids, upload=False)
    first = svc.catch_up(doc_ids, upload=False)
    second = svc.catch_up(doc_ids, upload=False)
    assert first == expect and second == expect
    st = svc.delta_cache.stats()
    assert st["served"] == 6, st
    assert svc.pipeline_stats.get("delta_docs", 0) == 6


def test_service_delta_gate_off(monkeypatch):
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService

    monkeypatch.setenv("FLUID_TPU_CATCHUP_DELTADOWNLOAD", "off")
    svc = CatchupService(LocalOrderingService(), mesh=None)
    assert svc.delta_cache is None
