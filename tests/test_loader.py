"""Loader + drivers: create/load/catch-up, delta-manager gap repair,
disconnect/reconnect with resubmit, stashed pending state, replay driver,
file driver durability."""

import pytest

from fluidframework_tpu.drivers import (
    FileDocumentServiceFactory,
    LocalDocumentServiceFactory,
    ReplayDocumentService,
)
from fluidframework_tpu.loader import ConnectionState, Loader
from fluidframework_tpu.service import LocalOrderingService


def make_stack():
    service = LocalOrderingService()
    factory = LocalDocumentServiceFactory(service)
    return service, factory, Loader(factory)


def build_text_doc(runtime):
    ds = runtime.create_datastore("ds")
    ds.create_channel("sequence-tpu", "text")
    ds.create_channel("map-tpu", "meta")


def text_of(container):
    return container.runtime.get_datastore("ds").get_channel("text").text


def text_channel(container):
    return container.runtime.get_datastore("ds").get_channel("text")


def map_channel(container):
    return container.runtime.get_datastore("ds").get_channel("meta")


# --- create / load / catch-up ------------------------------------------------


def test_create_then_load_and_collaborate():
    _service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    assert a.connected
    text_channel(a).insert_text(0, "hello")
    a.drain()

    b = loader.resolve("doc", "bob")
    assert text_of(b) == "hello"
    text_channel(b).insert_text(5, " world")
    a.drain()
    b.drain()
    assert text_of(a) == text_of(b) == "hello world"
    assert a.audience.members == ["alice", "bob"]
    assert b.audience.members == ["alice", "bob"]


def test_load_detached_read_only():
    _service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "content")
    a.drain()

    ro = loader.resolve("doc", client_id=None)
    assert not ro.connected
    assert text_of(ro) == "content"


def test_catchup_replay_from_summary_and_tail():
    """A late joiner loads the uploaded summary and replays only the tail."""
    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "0123456789")
    a.drain()
    # Central summary point
    from fluidframework_tpu.service.catchup import CatchupService
    CatchupService(service).catch_up()
    # More edits after the summary
    text_channel(a).insert_text(10, "-tail")
    a.drain()

    c = loader.resolve("doc", "carol")
    assert text_of(c) == "0123456789-tail"
    a.drain()  # alice must fold carol's JOIN before states can match
    sa = a.runtime.summarize().digest()
    sc = c.runtime.summarize().digest()
    assert sa == sc


def test_audience_includes_pre_summary_members():
    """Members whose JOIN is folded into the loaded summary (not in the
    replayed tail) must still appear in a late joiner's audience."""
    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "x")
    a.drain()
    from fluidframework_tpu.service.catchup import CatchupService
    CatchupService(service).catch_up()  # summary now covers alice's JOIN

    c = loader.resolve("doc", "carol")
    assert c.audience.members == ["alice", "carol"]


# --- delta manager: gaps, disconnect/reconnect -------------------------------


class LossyConnection:
    """Wraps a document-service connection, dropping selected live
    broadcasts (transport fault injection)."""

    def __init__(self, inner, drop_seqs):
        self._inner = inner
        self._drop = set(drop_seqs)
        self._subs = []
        inner.subscribe(self._relay)

    def _relay(self, msg):
        if msg.seq in self._drop:
            return
        for fn in list(self._subs):
            fn(msg)

    def subscribe(self, fn):
        self._subs.append(fn)

    def unsubscribe(self, fn):
        if fn in self._subs:
            self._subs.remove(fn)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_delta_manager_repairs_gaps_from_storage():
    service, factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    doc_service = factory.resolve("doc")
    head = service.oplog.head("doc")
    # bob's transport drops the next two sequenced messages
    lossy = LossyConnection(doc_service.connection(),
                            drop_seqs={head + 2, head + 3})
    doc_service._connection = lossy

    b = Loader(factory).resolve("doc")  # detached first
    b.delta_manager._service = doc_service
    b.runtime.connect(b.delta_manager, "bob")
    b.drain()

    text_channel(a).insert_text(0, "abc")   # dropped for bob
    text_channel(a).insert_text(3, "def")   # dropped for bob
    text_channel(a).insert_text(6, "ghi")   # delivered -> gap detected
    a.drain()
    b.drain()
    assert b.delta_manager.gaps_repaired >= 1
    assert text_of(b) == text_of(a) == "abcdefghi"


def test_disconnect_reconnect_resubmits_pending():
    """Offline edits are held locally and ride out on reconnect; concurrent
    remote edits merge."""
    _service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    b = loader.resolve("doc", "bob")

    b.disconnect()
    assert b.connection_state is ConnectionState.DISCONNECTED
    with pytest.raises(ConnectionError):
        b.delta_manager.submit(None)
    # channel-level edits while offline: applied optimistically, held
    text_channel(b).insert_text(0, "offline-edit ")
    map_channel(b).set("who", "bob")
    assert text_of(b) == "offline-edit "
    # concurrent remote edit
    text_channel(a).insert_text(0, "alice-edit ")
    a.drain()

    b.reconnect()
    a.drain()
    b.drain()
    assert text_of(a) == text_of(b)
    assert "offline-edit" in text_of(a) and "alice-edit" in text_of(a)
    assert map_channel(a).get("who") == "bob"


def test_read_only_mode_holds_ops_until_writable():
    """Read-only must not strand a diverged replica: local edits apply
    optimistically, are held unsent, and ride out when writability
    returns."""
    _service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    b = loader.resolve("doc", "bob")
    a.delta_manager.read_only = True
    text_channel(a).insert_text(0, "held ")
    b.drain()
    assert text_of(a) == "held "   # local optimistic apply
    assert text_of(b) == ""        # nothing sequenced
    # direct submit is still rejected loudly
    with pytest.raises(PermissionError):
        a.delta_manager.submit(None)
    a.delta_manager.read_only = False
    a.runtime.flush()
    a.drain()
    b.drain()
    assert text_of(b) == "held "


# --- stashed pending state ---------------------------------------------------


def test_pending_state_stash_and_rehydrate():
    """Close with unacked ops; rehydrate into a new session; converge."""
    service, factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "base")
    a.drain()

    b = loader.resolve("doc", "bob")
    # bob goes offline-ish: edits whose acks he never processes
    b.disconnect()
    b.reconnect()
    text_channel(b).insert_text(4, " pending")
    map_channel(b).set("k", "v")
    stash = b.close_and_get_pending_state()
    assert len(stash["pending"]) == 2

    # meanwhile alice keeps editing
    text_channel(a).insert_text(0, ">> ")
    a.drain()

    b2 = loader.resolve("doc", "bob2", pending_state=stash)
    a.drain()
    b2.drain()
    assert text_of(a) == text_of(b2)
    assert " pending" in text_of(a)
    assert ">> " in text_of(a)
    assert map_channel(a).get("k") == "v"


def test_stashed_op_already_sequenced_not_double_applied():
    """The crashed session's op made it into the durable log (the ack was
    just never processed): rehydrate must NOT re-apply the stashed copy."""
    _service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    b = loader.resolve("doc", "bob")
    # sequenced synchronously in-proc, but bob never drains the ack
    text_channel(b).insert_text(0, "once ")
    stash = b.close_and_get_pending_state()
    assert len(stash["pending"]) == 1

    b2 = loader.resolve("doc", "bob2", pending_state=stash)
    a.drain()
    b2.drain()
    assert text_of(a) == text_of(b2)
    assert text_of(a).count("once ") == 1


def test_stashed_never_sequenced_op_is_applied():
    """An op that never reached the sequencer (offline at close) must be
    re-applied and resubmitted by rehydrate."""
    _service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    b = loader.resolve("doc", "bob")
    b.disconnect()
    text_channel(b).insert_text(0, "ghost ")  # held: never sequenced
    stash = b.close_and_get_pending_state()
    assert len(stash["pending"]) == 1

    b2 = loader.resolve("doc", "bob2", pending_state=stash)
    a.drain()
    b2.drain()
    assert text_of(a) == text_of(b2)
    assert text_of(a).count("ghost ") == 1


def test_pending_state_empty_rehydrate():
    _service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "x")
    a.drain()
    stash = a.close_and_get_pending_state()
    assert stash["pending"] == []
    a2 = loader.resolve("doc", "alice2", pending_state=stash)
    assert text_of(a2) == "x"


# --- replay driver -----------------------------------------------------------


def test_replay_driver_reconstructs_history():
    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    lengths = {}
    for i in range(5):
        text_channel(a).insert_text(0, f"[{i}]")
        a.drain()
        lengths[service.oplog.head("doc")] = len(text_of(a))

    class ReplayFactory:
        def __init__(self, to_seq):
            self.to_seq = to_seq

        def resolve(self, doc_id):
            return ReplayDocumentService(
                doc_id, service.oplog, service.storage, to_seq=self.to_seq
            )

    for seq, expect_len in lengths.items():
        replayed = Loader(ReplayFactory(seq)).resolve("doc")
        assert len(text_of(replayed)) == expect_len
    # full replay matches the live document byte-for-byte
    full = Loader(ReplayFactory(None)).resolve("doc")
    assert full.runtime.summarize().digest() == \
        a.runtime.summarize().digest()


def test_replay_driver_rejects_writes():
    service, _factory, loader = make_stack()
    loader.create("doc", "alice", build_text_doc)

    class ReplayFactory:
        def resolve(self, doc_id):
            return ReplayDocumentService(
                doc_id, service.oplog, service.storage
            )

    ro = Loader(ReplayFactory()).resolve("doc")
    with pytest.raises(PermissionError):
        ro.delta_manager._service.connection().submit(None)


# --- file driver -------------------------------------------------------------


def test_file_driver_durable_across_reopen(tmp_path):
    root = str(tmp_path / "store")
    factory = FileDocumentServiceFactory(root)
    loader = Loader(factory)
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "durable")
    map_channel(a).set("version", 3)
    a.drain()
    digest = a.runtime.summarize().digest()
    factory.close()

    factory2 = FileDocumentServiceFactory(root)
    ro = Loader(factory2).resolve("doc")  # detached: byte-compare state
    assert ro.runtime.summarize().digest() == digest
    b = Loader(factory2).resolve("doc", "bob")
    assert text_of(b) == "durable"
    assert map_channel(b).get("version") == 3
    # still writable after reopen
    text_channel(b).insert_text(0, "still-")
    b.drain()
    assert text_of(b) == "still-durable"
    factory2.close()


# --- stale pending: rebase at reconnect / rehydrate --------------------------


def _advance_window(a, edits=8):
    """Drive alice's view (and so the MSN, once she is the only connected
    client) forward with edits that create and collect tombstones."""
    for i in range(edits):
        text_channel(a).insert_text(0, f"a{i}-")
        a.drain()
    t = text_of(a)
    if len(t) > 6:
        text_channel(a).remove_range(0, 4)
        a.drain()


def test_reconnect_rebases_stale_pending(monkeypatch):
    """Pending ops whose view fell below the collaboration window are
    regenerated against the current view at reconnect (not StaleOpError)."""
    from fluidframework_tpu.dds.sequence import SharedString

    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "base-text")
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()

    b.disconnect()
    text_channel(b).insert_text(4, "[bob]")
    text_channel(b).remove_range(0, 2)
    map_channel(b).set("who", "bob")
    _advance_window(a)  # MSN moves past bob's pinned views

    rebased = []
    orig = SharedString._resubmit_rebased
    monkeypatch.setattr(
        SharedString, "_resubmit_rebased",
        lambda self, pending: rebased.append(len(pending))
        or orig(self, pending),
    )
    b.reconnect()
    a.drain()
    b.drain()
    a.drain()

    assert rebased, "stale pending should have taken the rebase path"
    assert text_of(a) == text_of(b)
    assert "[bob]" in text_of(a)
    assert map_channel(a).get("who") == "bob"
    assert a.runtime.summarize().digest() == b.runtime.summarize().digest()


def test_rehydrate_rebases_stale_pending():
    """A stash whose refSeq fell below the collaboration window rehydrates
    by default: stashed ops re-applied at the stash point, then regenerated
    against the caught-up view."""
    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "0123456789")
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()

    b.disconnect()
    text_channel(b).insert_text(5, "<bob>")
    stash = b.close_and_get_pending_state()
    assert len(stash["pending"]) == 1
    _advance_window(a)

    b2 = loader.resolve("doc", "bob2", pending_state=stash)
    a.drain()
    b2.drain()
    a.drain()
    assert text_of(a) == text_of(b2)
    assert "<bob>" in text_of(a)
    assert a.runtime.summarize().digest() == b2.runtime.summarize().digest()


def test_rehydrate_stale_pending_drop_mode():
    """stale_pending='drop' still loads clean, discarding the stash."""
    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "0123456789")
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()
    b.disconnect()
    text_channel(b).insert_text(5, "<bob>")
    stash = b.close_and_get_pending_state()
    _advance_window(a)

    b2 = loader.resolve("doc", "bob2", pending_state=stash,
                        stale_pending="drop")
    a.drain()
    b2.drain()
    assert text_of(a) == text_of(b2)
    assert "<bob>" not in text_of(a)


def test_rebase_interval_anchor_excludes_later_pending_inserts():
    """A pending interval op regenerated at rebase must resolve endpoints
    without counting own pending inserts later in the FIFO (they sequence
    after it) — else the anchor shifts right on every replica."""
    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build_text_doc)
    text_channel(a).insert_text(0, "abcdef")
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()
    b.disconnect()
    iv_id = text_channel(b).add_interval(1, 2)  # over 'b'
    text_channel(b).insert_text(0, "ZZ")        # later in the pending FIFO
    _advance_window(a)
    b.reconnect()
    a.drain()
    b.drain()
    a.drain()
    assert text_of(a) == text_of(b)
    pa = text_channel(a).get_interval_collection().endpoints(iv_id)
    pb = text_channel(b).get_interval_collection().endpoints(iv_id)
    assert pa == pb
    s, e = pa
    assert text_of(a)[s:e] == "b"


def test_rebase_register_write_keeps_unobserved_versions():
    """A stale register write resubmits with its ORIGINAL ref_seq: the
    supersede filter compares observation points, so re-pinning to the
    current view would wipe concurrent versions the author never saw."""
    service, _factory, loader = make_stack()

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("register-collection-tpu", "reg")
        ds.create_channel("sequence-tpu", "text")

    def reg(c):
        return c.runtime.get_datastore("ds").get_channel("reg")

    a = loader.create("doc", "alice", build)
    reg(a).write("k", "alice-v1")
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()
    b.disconnect()
    reg(b).write("k", "bob-v")
    reg(a).write("k", "alice-v2")
    a.drain()
    _advance_window(a)
    b.reconnect()
    a.drain()
    b.drain()
    a.drain()
    assert reg(a).read_versions("k") == reg(b).read_versions("k")
    assert set(reg(a).read_versions("k")) == {"alice-v2", "bob-v"}
    assert reg(a).read("k") == "alice-v2"


def test_stale_matrix_pending_rebases_at_rehydrate():
    """SharedMatrix pending ops now REBASE (round 3): a stale stash's
    setCell regenerates row/col from its resolved permutation handles at
    rehydrate and converges — no StaleOpError, no drop needed."""
    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("matrix-tpu", "grid")
        ds.create_channel("sequence-tpu", "text")

    def grid(c):
        return c.runtime.get_datastore("ds").get_channel("grid")

    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build)
    grid(a).insert_rows(0, 2)
    grid(a).insert_cols(0, 2)
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()
    b.disconnect()
    grid(b).set_cell(0, 0, "bob")
    stash = b.close_and_get_pending_state()  # crash offline: stale refSeq
    _advance_window(a)

    b2 = loader.resolve("doc", "bob2", pending_state=stash)
    a.drain()
    b2.drain()
    a.drain()
    assert grid(b2).get_cell(0, 0) == "bob"
    assert a.runtime.summarize().digest() == b2.runtime.summarize().digest()


def test_stale_matrix_setcell_on_removed_row_drops_cleanly():
    """A rebased setCell whose ROW was removed while the client was away
    drops (remote replicas would resolve the same nothing) and replicas
    converge."""
    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("matrix-tpu", "grid")
        ds.create_channel("sequence-tpu", "text")

    def grid(c):
        return c.runtime.get_datastore("ds").get_channel("grid")

    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build)
    grid(a).insert_rows(0, 2)
    grid(a).insert_cols(0, 2)
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()
    b.disconnect()
    grid(b).set_cell(0, 0, "bob")
    stash = b.close_and_get_pending_state()
    grid(a).remove_rows(0, 1)  # the cell's row dies while bob is away
    _advance_window(a)

    b2 = loader.resolve("doc", "bob2", pending_state=stash)
    a.drain()
    b2.drain()
    a.drain()
    assert grid(b2).get_cell(0, 0) is None  # row 0 is now the old row 1
    assert a.runtime.summarize().digest() == b2.runtime.summarize().digest()


def test_stale_matrix_reconnect_rebases_pending():
    """Reconnect with a stale matrix pending op now rebases it in place
    (previously a StaleOpError requiring stash-and-rehydrate)."""
    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("matrix-tpu", "grid")
        ds.create_channel("sequence-tpu", "text")

    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build)
    g = a.runtime.get_datastore("ds").get_channel("grid")
    g.insert_rows(0, 2)
    g.insert_cols(0, 2)
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()
    b.disconnect()
    b.runtime.get_datastore("ds").get_channel("grid").set_cell(0, 0, "bob")
    _advance_window(a)
    b.reconnect()
    a.drain()
    b.drain()
    a.drain()
    assert b.runtime.get_datastore("ds").get_channel("grid") \
        .get_cell(0, 0) == "bob"
    assert a.runtime.summarize().digest() == b.runtime.summarize().digest()


def test_stale_stash_with_already_sequenced_matrix_op_loads():
    """A stashed non-rebasable op that DID reach the sequencer is deduped
    at rehydrate, so a stale stash must not raise for it."""
    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("matrix-tpu", "grid")
        ds.create_channel("sequence-tpu", "text")

    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build)
    g = a.runtime.get_datastore("ds").get_channel("grid")
    g.insert_rows(0, 2)
    g.insert_cols(0, 2)
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()
    # The op is sequenced synchronously in-proc; bob never drains the ack.
    b.runtime.get_datastore("ds").get_channel("grid").set_cell(0, 0, "bob")
    stash = b.close_and_get_pending_state()
    assert len(stash["pending"]) == 1
    _advance_window(a)

    b2 = loader.resolve("doc", "bob2", pending_state=stash)  # no raise
    a.drain()
    b2.drain()
    assert b2.runtime.get_datastore("ds").get_channel("grid") \
        .get_cell(0, 0) == "bob"
    assert a.runtime.summarize().digest() == b2.runtime.summarize().digest()


# --- rehydrate exactness under nacks + heavy faults (round 3) ----------------


def _nack_stack(nack_every):
    counter = {"n": 0}

    def throttle(_cid):
        counter["n"] += 1
        if nack_every and counter["n"] % nack_every == 0:
            return 0.0
        return None

    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.drivers.local_driver import (
        LocalDocumentServiceFactory,
    )

    service = LocalOrderingService(throttle=throttle)
    return service, Loader(LocalDocumentServiceFactory(service))


def _text_build(rt):
    ds = rt.create_datastore("ds")
    ds.create_channel("sequence-tpu", "text")


def _pump(service, conts, rounds=16):
    for _ in range(rounds):
        for c in conts.values():
            if c.delta_manager.state.value != "connected":
                c.reconnect()
            c.runtime.flush()
            c.drain()
        head = service.oplog.head("doc")
        if all(c.runtime.ref_seq == head and not c.runtime._pending_wire
               and not c.runtime._outbox for c in conts.values()):
            return
    raise AssertionError("never quiesced")


def test_rehydrate_resubmit_regenerates_under_new_identity():
    """Fuzz-minimized: a stashed op resubmitted after rehydrate rides a NEW
    client id, so pinning it to the crashed session's ref would lie about
    own-op visibility (the old id's sequenced inserts count in that view,
    the new id's don't) — resubmission must regenerate against the current
    view."""
    service, loader = _nack_stack(nack_every=3)
    a = loader.create("doc", "A", _text_build)
    b = loader.resolve("doc", "B")
    conts = {"A": a, "B": b}
    ta = a.runtime.get_datastore("ds").get_channel("text")
    tb = b.runtime.get_datastore("ds").get_channel("text")
    ta.insert_text(0, "abcd")
    ta.insert_text(len(ta.text), "xx")
    tb.insert_text(len(tb.text), "xx")
    n = len(ta.text)
    ta.remove_range(1, 3)
    n = len(tb.text)
    tb.remove_range(min(5, n - 1), min(n, min(5, n - 1) + 2))
    stash = conts["B"].close_and_get_pending_state()
    conts["B"] = loader.resolve("doc", "B1", pending_state=stash)
    _pump(service, conts)
    assert conts["A"].runtime.summarize().digest() == \
        conts["B"].runtime.summarize().digest()


def test_rehydrate_replays_own_sequenced_ops_at_their_refs():
    """Fuzz-minimized: the crashed session's own ops SEQUENCED in the tail
    were still pending when later stashed ops were authored — the load
    point must drop to their authoring refs (a fixpoint) and the replay
    must re-apply them as optimistic context, acked by their wire copies
    through identity adoption."""
    service, loader = _nack_stack(nack_every=3)
    a = loader.create("doc", "A", _text_build)
    b = loader.resolve("doc", "B")
    c = loader.resolve("doc", "C")
    conts = {"A": a, "B": b, "C": c}

    def t(w):
        return conts[w].runtime.get_datastore("ds").get_channel("text")

    t("A").insert_text(0, "abcd")
    for w in "ABC":
        conts[w].drain()
    t("B").insert_text(min(12, len(t("B").text)), "xx")
    t("B").insert_text(min(10, len(t("B").text)), "y")
    t("C").insert_text(min(8, len(t("C").text)), "y")
    conts["B"].drain()
    t("B").insert_text(min(8, len(t("B").text)), "y")
    conts["B"].drain()
    stash = conts["B"].close_and_get_pending_state()
    conts["B"] = loader.resolve("doc", "B1", pending_state=stash)
    _pump(service, conts)
    digests = {x.runtime.summarize().digest() for x in conts.values()}
    assert len(digests) == 1, {w: t(w).text for w in conts}


def test_load_heavy_faults_with_nacks_and_stashes_converges():
    """The load-harness shape that found the rehydrate divergences:
    nack fault injection + disconnects + stash/rehydrate chains."""
    from fluidframework_tpu.testing.load import LoadSpec, run_load

    for seed in (4, 11, 13, 39):
        result = run_load(LoadSpec(
            seed=seed, clients=4, steps=250, nack_every=7,
            disconnect_weight=0.12, stash_weight=0.08,
            late_join_weight=0.04, edit_weight=0.55, sync_weight=0.21,
        ))
        assert len(result.summary_digest) == 64
        assert result.rehydrates > 0


def test_rehydrate_matrix_insert_ack_keeps_wire_attribution():
    """A stashed matrix insert_rows sequenced under the crashed session's
    id and acked via adoption must keep the WIRE attribution (review-found:
    the local ack path dropped the client id, leaving the new session's id
    on the segment while remotes recorded the old one)."""
    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("matrix-tpu", "grid")

    service, _factory, loader = make_stack()
    a = loader.create("doc", "alice", build)
    g = a.runtime.get_datastore("ds").get_channel("grid")
    g.insert_rows(0, 1)
    g.insert_cols(0, 1)
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()
    gb = b.runtime.get_datastore("ds").get_channel("grid")
    gb.insert_rows(1, 2)   # submits; sequenced...
    b.runtime.flush()
    stash = b.close_and_get_pending_state()  # ...but the ack never drained
    b2 = loader.resolve("doc", "bob2", pending_state=stash)
    a.drain()
    b2.drain()
    a.drain()
    assert a.runtime.summarize().digest() == b2.runtime.summarize().digest()


def test_rehydrate_clears_stale_predicted_obliterate_kill():
    """Fuzz-minimized: a pending insert predicted-killed by a concurrent
    obliterate at its OLD position must shed that verdict when rehydrate
    regenerates it — the fresh in-window resubmission can never be killed
    on arrival (every stamp is already seen), and remotes keep it alive."""
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.drivers.local_driver import (
        LocalDocumentServiceFactory,
    )

    counter = {"n": 0}

    def throttle(_cid):
        counter["n"] += 1
        return 0.0 if counter["n"] % 5 == 0 else None

    service = LocalOrderingService(throttle=throttle)
    loader = Loader(LocalDocumentServiceFactory(service))

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")

    conts = {"A": loader.create("doc", "A", build),
             "B": loader.resolve("doc", "B")}

    def t(w):
        return conts[w].runtime.get_datastore("ds").get_channel("text")

    t("A").insert_text(0, "abcdef")
    conts["B"].drain()
    n = len(t("A").text)
    s0 = min(6, n - 1)
    t("A").remove_range(s0, min(n, s0 + 2))
    t("A").insert_text(min(5, len(t("A").text)), "y")
    n = len(t("A").text)
    t("A").obliterate_range(1, min(n, 3))
    t("A").insert_text(min(3, len(t("A").text)), "y")
    n = len(t("B").text)
    s0 = min(4, n - 1)
    t("B").obliterate_range(s0, min(n, s0 + 2))
    stash = conts["A"].close_and_get_pending_state()
    conts["A"] = loader.resolve("doc", "A1", pending_state=stash)
    for _ in range(16):
        for c in conts.values():
            if c.delta_manager.state.value != "connected":
                c.reconnect()
            c.runtime.flush()
            c.drain()
        head = service.oplog.head("doc")
        if all(c.runtime.ref_seq == head and not c.runtime._pending_wire
               and not c.runtime._outbox for c in conts.values()):
            break
    digests = {c.runtime.summarize().digest() for c in conts.values()}
    assert len(digests) == 1, {w: t(w).text for w in conts}


def test_rehydrate_restores_demoted_pending_remove_on_cleared_kill():
    """Review-found: clearing a stale predicted-kill must restore a local
    pending removal the kill had demoted, or the regenerated remove never
    marks the segment removed locally while every remote applies it."""
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.drivers.local_driver import (
        LocalDocumentServiceFactory,
    )

    service = LocalOrderingService()
    loader = Loader(LocalDocumentServiceFactory(service))

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")

    a = loader.create("doc", "A", build)
    b = loader.resolve("doc", "B")
    ta = a.runtime.get_datastore("ds").get_channel("text")
    tb = b.runtime.get_datastore("ds").get_channel("text")
    ta.insert_text(0, "wxyz")
    a.drain()
    b.drain()
    b.disconnect()
    tb.insert_text(1, "abc")
    tb.remove_range(1, 4)          # removes its own pending text
    ta.obliterate_range(0, 3)      # concurrent kill over the slot
    a.drain()
    stash = b.close_and_get_pending_state()
    b2 = loader.resolve("doc", "B2", pending_state=stash)
    for _ in range(12):
        for c in (a, b2):
            if c.delta_manager.state.value != "connected":
                c.reconnect()
            c.runtime.flush()
            c.drain()
    t2 = b2.runtime.get_datastore("ds").get_channel("text")
    assert ta.text == t2.text
    assert a.runtime.summarize().digest() == b2.runtime.summarize().digest()
