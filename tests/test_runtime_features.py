"""Container-runtime op pipeline (compression, chunking), garbage
collection, and attachment blobs."""

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime.container import (
    ContainerRuntime,
    ContainerRuntimeOptions,
)
from fluidframework_tpu.runtime.gc import GCOptions
from fluidframework_tpu.runtime.handles import channel_handle
from fluidframework_tpu.service import LocalOrderingService
from fluidframework_tpu.service.catchup import CatchupService


def make_stack(registry=None, options=None):
    service = LocalOrderingService()
    factory = LocalDocumentServiceFactory(service)

    class OptLoader(Loader):
        def _new_runtime(self):
            return ContainerRuntime(self.registry, options)

    return service, OptLoader(factory, registry)


def build_doc(rt):
    ds = rt.create_datastore("ds")
    ds.create_channel("map-tpu", "kv")
    ds.create_channel("sequence-tpu", "text")


def kv(c):
    return c.runtime.get_datastore("ds").get_channel("kv")


def text(c):
    return c.runtime.get_datastore("ds").get_channel("text")


# --- compression / chunking --------------------------------------------------


def test_large_batch_is_compressed_on_wire():
    opts = ContainerRuntimeOptions(compression_threshold=256)
    service, loader = make_stack(options=opts)
    a = loader.create("doc", "alice", build_doc)
    b = loader.resolve("doc", "bob")
    kv(a).set("big", "x" * 2000)
    a.drain()
    b.drain()
    wire = [m for m in service.oplog.get("doc")
            if isinstance(m.contents, dict)
            and m.contents.get("type") == "compressedBatch"]
    assert wire, "batch should have been compressed on the wire"
    assert kv(b).get("big") == "x" * 2000
    assert (a.runtime.summarize().digest()
            == b.runtime.summarize().digest())


def test_huge_batch_is_chunked_and_reassembled():
    opts = ContainerRuntimeOptions(compression_threshold=10**9,  # no compress
                                   chunk_size=512)
    service, loader = make_stack(options=opts)
    a = loader.create("doc", "alice", build_doc)
    b = loader.resolve("doc", "bob")
    payload = "".join(chr(ord("a") + i % 26) for i in range(4000))
    kv(a).set("huge", payload)
    a.drain()
    b.drain()
    chunks = [m for m in service.oplog.get("doc")
              if isinstance(m.contents, dict)
              and m.contents.get("type") == "chunk"]
    assert len(chunks) >= 2
    assert kv(b).get("huge") == payload
    # a late joiner replays the chunked log correctly too
    c = loader.resolve("doc", "carl")
    assert kv(c).get("huge") == payload


def test_compressed_and_chunked_together_with_device_catchup():
    """Chunk+compress the wire, then let the bulk catch-up service decode
    the same stream — string doc stays device-eligible."""
    opts = ContainerRuntimeOptions(compression_threshold=128, chunk_size=256)
    service, loader = make_stack(options=opts)

    def build(rt):
        rt.create_datastore("ds").create_channel("sequence-tpu", "text")

    a = loader.create("doc", "alice", build)
    t = a.runtime.get_datastore("ds").get_channel("text")
    with a.runtime.order_sequentially():
        for i in range(40):
            t.insert_text(len(t.text), f"chunk-me-{i:03d} ")
    a.drain()

    svc = CatchupService(service)
    svc.catch_up()
    assert svc.device_docs == 1
    fresh = loader.resolve("doc")
    assert fresh.runtime.get_datastore("ds").get_channel("text").text \
        == t.text


# --- garbage collection ------------------------------------------------------


def test_gc_sweeps_unreferenced_datastore():
    opts = ContainerRuntimeOptions(gc=GCOptions(sweep_grace_ops=3))
    service, loader = make_stack(options=opts)
    a = loader.create("doc", "alice", build_doc)
    # a non-rooted datastore referenced from the rooted one
    side = a.runtime.create_datastore("side", rooted=False)
    side.create_channel("map-tpu", "data")
    kv(a).set("ref", channel_handle("side", "data"))
    a.drain()
    state = a.runtime.summarize()
    assert "side" in state.get(".datastores").children

    # drop the reference; after grace ops, sweep
    kv(a).delete("ref")
    a.drain()
    s1 = a.runtime.summarize()
    import json
    gc1 = json.loads(s1.blob_bytes(".gc"))
    assert "side" in gc1["unreferenced"]
    for i in range(4):
        kv(a).set(f"pad{i}", i)
        a.drain()
    # sweeping is a sequenced op: EVERY replica deletes at the same fold
    # position — and a replica that merely summarizes never mutates.
    assert a.runtime.perform_gc_sweep() == ["side"]
    a.drain()
    s2 = a.runtime.summarize()
    gc2 = json.loads(s2.blob_bytes(".gc"))
    assert "side" in gc2["swept"]
    assert "side" not in s2.get(".datastores").children
    assert "side" not in a.runtime.datastores


def test_gc_revival_clears_stamp():
    opts = ContainerRuntimeOptions(gc=GCOptions(sweep_grace_ops=100))
    _service, loader = make_stack(options=opts)
    a = loader.create("doc", "alice", build_doc)
    side = a.runtime.create_datastore("side", rooted=False)
    side.create_channel("map-tpu", "data")
    a.drain()
    s1 = a.runtime.summarize()
    import json
    assert "side" in json.loads(s1.blob_bytes(".gc"))["unreferenced"]
    kv(a).set("ref", channel_handle("side", "data"))  # revive
    a.drain()
    s2 = a.runtime.summarize()
    assert json.loads(s2.blob_bytes(".gc"))["unreferenced"] == {}


def test_gc_state_rides_summary_to_loader():
    opts = ContainerRuntimeOptions(gc=GCOptions(sweep_grace_ops=100))
    service, loader = make_stack(options=opts)
    a = loader.create("doc", "alice", build_doc)
    a.runtime.create_datastore("orphan", rooted=False) \
        .create_channel("map-tpu", "x")
    a.drain()
    service.storage.upload("doc", a.runtime.summarize(),
                           a.runtime.ref_seq)  # stamps orphan
    b = loader.resolve("doc", "bob")
    # bob inherits the stamp through his loaded summary
    assert "orphan" in b.runtime.gc.unreferenced_at


# --- attachment blobs --------------------------------------------------------


def test_blob_roundtrip_and_replication():
    service, loader = make_stack()
    a = loader.create("doc", "alice", build_doc)
    b = loader.resolve("doc", "bob")
    payload = bytes(range(256)) * 10
    handle = a.runtime.blob_manager.create_blob(payload)
    kv(a).set("attachment", handle)
    a.drain()
    b.drain()
    assert b.runtime.blob_manager.get_blob(kv(b).get("attachment")) \
        == payload
    # referenced blob rides the summary to a late joiner
    c = loader.resolve("doc", "carl")
    assert c.runtime.blob_manager.get_blob(kv(c).get("attachment")) \
        == payload
    assert (a.runtime.summarize().digest()
            == b.runtime.summarize().digest())


def test_unreferenced_blob_kept_through_grace_then_dropped():
    """Blob bytes must survive the grace window (a handle written in the
    post-summary tail still needs them), then drop."""
    opts = ContainerRuntimeOptions(gc=GCOptions(sweep_grace_ops=3))
    _service, loader = make_stack(options=opts)
    a = loader.create("doc", "alice", build_doc)
    handle = a.runtime.blob_manager.create_blob(b"ephemeral")
    kv(a).set("att", handle)
    a.drain()
    sha = handle["fluidBlob"]
    s1 = a.runtime.summarize()
    assert sha in s1.get(".blobs").children
    kv(a).delete("att")
    a.drain()
    s2 = a.runtime.summarize()  # stamps the blob, still within grace
    assert sha in s2.get(".blobs").children
    for i in range(4):
        kv(a).set(f"pad{i}", i)
        a.drain()
    s3 = a.runtime.summarize()  # grace expired
    assert sha not in s3.get(".blobs").children


def test_blob_referenced_after_summary_point_survives():
    """Regression (review-found): blob attached at seq N, handle written at
    seq N+1; a loader of summary@N + tail must still resolve the blob."""
    service, loader = make_stack()
    a = loader.create("doc", "alice", build_doc)
    handle = a.runtime.blob_manager.create_blob(b"late-referenced")
    a.drain()
    # summarize + upload BEFORE any handle references the blob
    service.storage.upload("doc", a.runtime.summarize(), a.runtime.ref_seq)
    kv(a).set("att", handle)
    a.drain()
    b = loader.resolve("doc", "bob")
    assert b.runtime.blob_manager.get_blob(kv(b).get("att")) \
        == b"late-referenced"


def test_discarded_unsent_idrange_rolls_back_and_refinalizes():
    """An idRange consumed into a wire batch that never reached the
    sequencer must re-attach on the next flush (reconnect path), so the
    minted locals still finalize on every replica."""
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalOrderingService

    def build(rt):
        ds = rt.create_datastore("ds")
        ds.create_channel("map-tpu", "kv")

    service = LocalOrderingService()
    loader = Loader(LocalDocumentServiceFactory(service))
    a = loader.create("doc", "alice", build)
    a.drain()
    b = loader.resolve("doc", "bob")
    b.drain()

    comp = b.runtime.id_compressor
    local = comp.generate()           # mint a local id
    # A flush that encodes the batch (taking the creation range into the
    # wire message) but whose send fails: the range sits in _pending_wire.
    service_obj = b.runtime._service
    orig_submit = service_obj.submit

    def failing_submit(raw):
        raise ConnectionError("link dropped mid-send")

    service_obj.submit = failing_submit
    try:
        # The send failure is absorbed: the encoded batch (with its taken
        # idRange) waits in _pending_wire and the op stays pending.
        b.runtime.get_datastore("ds").get_channel("kv").set("id", local)
    finally:
        service_obj.submit = orig_submit
    assert any(g is not None for _op, g in b.runtime._pending_wire), (
        "test setup: the failed batch should hold a taken idRange"
    )
    b.disconnect()
    b.reconnect()                     # discards unsent wire, resubmits
    a.drain()
    b.drain()
    a.drain()
    # The range re-attached: bob's local finalizes everywhere.
    assert comp.normalize_to_op_space(local) >= 0, (
        "rolled-back creation range never re-attached/finalized"
    )
    assert a.runtime.summarize().digest() == b.runtime.summarize().digest()


def test_chunk_reassembler_rejects_malformed_chunks():
    from fluidframework_tpu.runtime.op_pipeline import ChunkReassembler

    r = ChunkReassembler()
    assert r.feed("c", {"total": 2, "index": 0, "data": "aGk="}) is None
    # malformed: index beyond total — state resets, no crash
    assert r.feed("c", {"total": 2, "index": 5, "data": "aGk="}) is None
    # total mismatch with a fresh partial train — state resets
    assert r.feed("c", {"total": 3, "index": 0, "data": "aGk="}) is None
    assert r.feed("c", {"total": 2, "index": 0, "data": "aGk="}) is None
    assert r.feed("c", {"total": -1, "index": 0, "data": "aGk="}) is None
    assert r.feed("c", {"total": True, "index": 0, "data": "x"}) is None
