"""SharedCell / SharedCounter + regression tests from review findings."""

from fluidframework_tpu.dds import SharedCell, SharedCounter, SharedDirectory, SharedString
from fluidframework_tpu.testing import MockContainerRuntimeFactory


def make_pair(cls):
    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(cls("x"))
    b = factory.create_client("B").attach(cls("x"))
    return factory, a, b


def test_cell_lww_and_pending_priority():
    factory, a, b = make_pair(SharedCell)
    a.set(1)
    factory.process_all_messages()
    b.set(2)
    a.set(3)  # sequenced after b's → wins; pending must mask b's op
    factory.process_all_messages()
    assert a.get() == b.get() == 3
    assert a.summarize().digest() == b.summarize().digest()


def test_cell_delete():
    factory, a, b = make_pair(SharedCell)
    a.set("v")
    factory.process_all_messages()
    b.delete()
    factory.process_all_messages()
    assert a.is_empty and b.is_empty


def test_counter_increments_commute():
    factory, a, b = make_pair(SharedCounter)
    a.increment(5)
    b.increment(-2)
    a.increment(1)
    factory.process_all_messages()
    assert a.value == b.value == 4
    assert a.summarize().digest() == b.summarize().digest()


def test_directory_concurrent_create_then_delete_converges():
    """Regression: deleteSubdir must re-apply on local ack so a concurrent
    create sequenced before the delete doesn't resurrect the subdir on the
    deleting replica only."""
    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(SharedDirectory("d"))
    b = factory.create_client("B").attach(SharedDirectory("d"))
    b.create_subdirectory("sub")  # sequenced first
    a.delete_subdirectory("sub")  # concurrent, sequenced second
    factory.process_all_messages()
    assert a.summarize().digest() == b.summarize().digest()
    assert a.root.resolve("sub") is None and b.root.resolve("sub") is None
    # Opposite order: delete first, create second → subdir exists everywhere.
    a2 = factory.create_client("A2").attach(SharedDirectory("d2"))
    b2 = factory.create_client("B2").attach(SharedDirectory("d2"))
    a2.delete_subdirectory("sub")
    b2.create_subdirectory("sub")
    factory.process_all_messages()
    assert a2.summarize().digest() == b2.summarize().digest()
    assert a2.root.resolve("sub") is not None


def test_string_load_discards_inflight_pending():
    """Regression: load() must clear the base pending deque too, or acks of
    pre-load ops crash."""
    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(SharedString("s"))
    a.insert_text(0, "committed")
    factory.process_all_messages()
    summary = a.summarize()
    a.insert_text(0, "in-flight-")  # submitted but not yet sequenced
    a.load(summary)
    factory.process_all_messages()  # the stale ack must not crash or apply
    assert a.text == "committed"


def test_stay_on_remove_reference_pins_tombstone():
    """Regression: slide=False refs stay attached to the removed segment and
    keep zamboni from collecting it."""
    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(SharedString("s"))
    a.insert_text(0, "abcdef")
    factory.process_all_messages()
    ref = a.tree.create_reference(2, client="A", slide=False)
    pinned = ref.segment
    a.remove_range(0, 6)
    factory.process_all_messages()
    factory.advance_min_seq()
    assert ref.segment is pinned
    assert pinned in a.tree.segments  # not collected
    assert a.tree.reference_position(ref) == 0  # at a removed segment


def test_sliding_reference_moves_to_survivor():
    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(SharedString("s"))
    a.insert_text(0, "abcdef")
    factory.process_all_messages()
    ref = a.tree.create_reference(1, client="A")  # inside 'abcdef'
    a.remove_range(0, 3)
    factory.process_all_messages()
    # Slid forward to the start of the surviving "def".
    assert a.tree.reference_position(ref, client="A") == 0
    assert ref.segment is not None and ref.segment.text == "def"
