"""faultline (ISSUE 9): deterministic fault injection across the serving
stack, the retry/backoff machinery the faults force, and the
crash-recovery oracle.

The load-bearing acceptance test drives mixed multi-shard traffic under a
generated fault schedule covering ≥5 fault kinds (durable-append failure,
torn append, stale summary serve, shard kill, stalled/laggard client) at
several seeds and asserts final per-document summaries BYTE-IDENTICAL to
a fault-free oracle run — faults may cost retries and recoveries, never
state — plus bit-identical telemetry counters on replay of the same
(seed, plan).
"""

import os
import threading
import time

import pytest

from fluidframework_tpu.drivers.file_driver import FileSummaryStorage
from fluidframework_tpu.drivers.local_driver import (
    LocalDocumentServiceFactory,
)
from fluidframework_tpu.drivers.network_driver import (
    NetworkDocumentServiceFactory, RpcError,
)
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.loader.delta_manager import DeltaManager
from fluidframework_tpu.protocol.messages import (
    MessageType, NackError, RawOperation, RetryBudgetExhaustedError,
)
from fluidframework_tpu.protocol.sequencer import Sequencer
from fluidframework_tpu.protocol.summary import SummaryTree
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.oplog import OpLog
from fluidframework_tpu.service.orderer import LocalOrderingService
from fluidframework_tpu.service.retry import RetryPolicy
from fluidframework_tpu.service.server import OrderingServer
from fluidframework_tpu.service.sharding import ShardedOrderingService
from fluidframework_tpu.testing.faults import (
    FaultError, FaultInjector, FaultPlan, FaultPoint,
)
from fluidframework_tpu.testing.load import (
    ChaosLoadSpec, VirtualClock, run_chaos_load, run_chaos_with_oracle,
)


def _msg(seq, client="c", contents=None):
    from fluidframework_tpu.protocol.messages import SequencedMessage

    return SequencedMessage(seq=seq, client_id=client, client_seq=seq,
                            ref_seq=0, min_seq=0, type=MessageType.OP,
                            contents=contents or {"i": seq})


def _op(client, client_seq, ref_seq=0):
    return RawOperation(client_id=client, client_seq=client_seq,
                        ref_seq=ref_seq, type=MessageType.OP,
                        contents={"n": client_seq})


# --- the engine ---------------------------------------------------------------


def test_plan_validates_sites_and_kinds():
    with pytest.raises(ValueError):
        FaultPlan(points=(FaultPoint("nope.site", "fail"),))
    with pytest.raises(ValueError):
        FaultPlan(points=(FaultPoint("oplog.append", "stall"),))
    with pytest.raises(ValueError):
        FaultPlan(points=(FaultPoint("oplog.append", "fail", count=0),))


def test_injector_matches_by_occurrence_and_doc_scope():
    plan = FaultPlan(points=(
        FaultPoint("oplog.append", "fail", doc="d9", at=1),         # scoped
        FaultPoint("oplog.append", "fail", at=2, count=2),          # global
    ))
    inj = FaultInjector(plan)
    # global occurrence 1 -> no fault; d9's first append matches the
    # (earlier-listed) scoped point and consumes that occurrence
    assert inj.fire("oplog.append", doc="a") is None
    assert inj.fire("oplog.append", doc="d9").doc == "d9"
    assert inj.fire("oplog.append", doc="a").doc is None   # global #3 >= at
    assert inj.fire("oplog.append", doc="a").doc is None   # count=2
    assert inj.fire("oplog.append", doc="a") is None       # exhausted
    assert inj.unfired() == []
    snap = inj.snapshot()
    assert snap["oplog.append:fail"] == 3


def test_injector_shadowed_point_fires_on_next_occurrence():
    plan = FaultPlan(points=(
        FaultPoint("oplog.append", "fail", at=1),
        FaultPoint("oplog.append", "torn", at=1),
    ))
    inj = FaultInjector(plan)
    assert inj.fire("oplog.append").kind == "fail"
    assert inj.fire("oplog.append").kind == "torn"  # deferred, not lost
    assert inj.unfired() == []


def test_scheduled_points_fire_once_by_tick():
    plan = FaultPlan(points=(FaultPoint("shard.kill", "kill", at=10),))
    inj = FaultInjector(plan)
    assert inj.due("shard.kill", 9) == []
    assert [p.at for p in inj.due("shard.kill", 10)] == [10]
    assert inj.due("shard.kill", 11) == []  # once
    assert inj.unfired() == []


def test_generated_plan_is_deterministic_and_covers_required_kinds():
    docs = [f"d{i}" for i in range(6)]
    a = FaultPlan.generate(7, docs, 200)
    b = FaultPlan.generate(7, docs, 200)
    assert a == b
    kinds = {(p.site, p.kind) for p in a.points}
    assert ("oplog.append", "fail") in kinds
    assert ("oplog.append", "torn") in kinds
    assert ("shard.kill", "kill") in kinds
    assert ("client.stall", "stall") in kinds
    assert ("storage.read", "stale") in kinds


# --- RetryPolicy --------------------------------------------------------------


def test_retry_backoff_is_deterministic_and_bounded():
    import random

    policy = RetryPolicy(max_attempts=4, base_delay=0.1, multiplier=2.0,
                         max_delay=10.0, jitter=0.5)
    a = [policy.delay_for(n, random.Random(42)) for n in range(1, 5)]
    b = [policy.delay_for(n, random.Random(42)) for n in range(1, 5)]
    assert a == b  # pure function of (attempt, rng state)
    for n, d in enumerate(a, start=1):
        raw = 0.1 * 2.0 ** (n - 1)
        assert raw / 2 <= d <= raw  # jitter only shortens


def test_retry_succeeds_after_transient_failures_and_counts():
    from fluidframework_tpu.utils.telemetry import LockedCounterSet

    clock = VirtualClock()
    counters = LockedCounterSet()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    policy = RetryPolicy(max_attempts=5, base_delay=0.01)
    assert policy.run(flaky, sleep=clock.sleep,
                      counters=counters) == "ok"
    assert calls["n"] == 3
    assert counters.get("retry.retries") == 2
    assert counters.get("retry.exhausted") == 0
    assert clock.now > 0  # really backed off, in virtual time


def test_retry_budget_exhaustion_is_typed_and_bounded():
    clock = VirtualClock()
    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise OSError("down for good")

    policy = RetryPolicy(max_attempts=4, base_delay=0.01, budget=10.0)
    with pytest.raises(RetryBudgetExhaustedError) as exc_info:
        policy.run(always_fails, operation="test-op", sleep=clock.sleep)
    assert calls["n"] == 4  # NEVER unbounded
    err = exc_info.value
    assert err.attempts == 4
    assert isinstance(err.last_error, OSError)
    assert isinstance(err, ConnectionError)  # wire-drain keeps ops queued


def test_retry_honors_nack_retry_after_and_no_retry_precedence():
    clock = VirtualClock()
    calls = {"n": 0}

    def nacked_once():
        calls["n"] += 1
        if calls["n"] == 1:
            raise NackError("throttled", retry_after=7.5)
        return "ok"

    policy = RetryPolicy(max_attempts=3, base_delay=0.01)
    assert policy.run(nacked_once, sleep=clock.sleep) == "ok"
    assert clock.now >= 7.5  # the server's pacing is never undercut

    def nacked():
        raise NackError("mine", retry_after=0.0)

    with pytest.raises(NackError):  # no_retry wins over the nack handler
        policy.run(nacked, sleep=clock.sleep, no_retry=(NackError,))


def test_retry_fence_re_resolves_instead_of_blind_retry():
    from fluidframework_tpu.protocol.messages import ShardFencedError

    resolved = {"n": 0}
    calls = {"n": 0}

    def fenced_until_resolved():
        calls["n"] += 1
        if not resolved["n"]:
            raise ShardFencedError("doc")
        return "ok"

    policy = RetryPolicy(max_attempts=3, base_delay=0.01)
    # without on_fence: a blind retry can never succeed -> re-raise now
    with pytest.raises(ShardFencedError):
        policy.run(fenced_until_resolved, sleep=lambda _s: None)
    assert calls["n"] == 1

    def re_resolve():
        resolved["n"] += 1

    assert policy.run(fenced_until_resolved, sleep=lambda _s: None,
                      on_fence=re_resolve) == "ok"
    assert resolved["n"] == 1


# --- oplog seam ---------------------------------------------------------------


def test_oplog_append_failure_is_exception_safe_in_memory():
    plan = FaultPlan(points=(FaultPoint("oplog.append", "fail", at=2),))
    log = OpLog(faults=FaultInjector(plan))
    log.append("d", _msg(1))
    with pytest.raises(FaultError):
        log.append("d", _msg(2))
    assert log.head("d") == 1  # nothing half-applied
    log.append("d", _msg(2))   # the retry lands cleanly
    assert log.head("d") == 2
    assert [m.seq for m in log.get("d")] == [1, 2]


def test_oplog_torn_append_self_repairs_the_file(tmp_path):
    path = str(tmp_path / "ops.jsonl")
    plan = FaultPlan(points=(
        FaultPoint("oplog.append", "torn", at=2, arg=0.4),))
    log = OpLog(path, autoflush=True, faults=FaultInjector(plan))
    log.append("d", _msg(1))
    with pytest.raises(OSError):
        log.append("d", _msg(2))
    assert log.head("d") == 1       # in-memory rolled back
    log.append("d", _msg(2))        # retry lands
    log.close()
    reopened = OpLog(path)          # file was self-repaired: no torn line
    assert [m.seq for m in reopened.get("d")] == [1, 2]
    reopened.close()


def test_oplog_flush_faults(tmp_path):
    path = str(tmp_path / "ops.jsonl")
    plan = FaultPlan(points=(
        FaultPoint("oplog.flush", "fail", at=1),
        FaultPoint("oplog.flush", "skip_fsync", at=2),
    ))
    log = OpLog(path, faults=FaultInjector(plan))
    log.append("d", _msg(1))
    with pytest.raises(FaultError):
        log.flush()
    log.flush()  # skip_fsync: succeeds, bytes reach the OS buffer
    log.close()
    assert [m.seq for m in OpLog(path).get("d")] == [1]


def test_oplog_reopen_dedups_duplicate_lines_keeping_the_last(tmp_path):
    """Duplicate-seq lines on disk: an identical retry resend, or a
    PHANTOM (bytes landed, fsync failed, rollback let a different op win
    the seq).  Reopen keeps the LAST line — what the live history
    actually broadcast — in both cases (review r2)."""
    path = tmp_path / "ops.jsonl"
    log = OpLog(str(path))
    log.append("d", _msg(1))
    log.append("d", _msg(2, contents={"winner": False}))
    log.close()
    # identical-retry duplicate of seq 2, then the phantom shape: a
    # DIFFERENT record at seq 2 appended last must win
    lines = path.read_text().splitlines()
    with open(path, "a", encoding="utf-8") as f:
        f.write(lines[1] + "\n")
    reopened = OpLog(str(path))
    assert [m.seq for m in reopened.get("d")] == [1, 2]
    reopened.close()
    phantom_first = OpLog(str(tmp_path / "phantom.jsonl"))
    phantom_first.append("d", _msg(1))
    phantom_first.append("d", _msg(2, contents={"winner": False}))
    phantom_first.close()
    real = OpLog(str(tmp_path / "real.jsonl"))
    real.append("d", _msg(2, contents={"winner": True}))
    real.close()
    with open(tmp_path / "phantom.jsonl", "a", encoding="utf-8") as f:
        f.write((tmp_path / "real.jsonl").read_text())
    merged = OpLog(str(tmp_path / "phantom.jsonl"))
    assert [m.seq for m in merged.get("d")] == [1, 2]
    assert merged.get("d")[-1].contents == {"winner": True}
    merged.close()


# --- sequencer exception safety ----------------------------------------------


def test_sequencer_rolls_back_stamp_when_durable_append_fails():
    seq = Sequencer()
    fail = {"armed": False}

    def durability_gate(msg):
        if fail["armed"]:
            fail["armed"] = False
            raise OSError("injected append failure")

    seq.subscribe(durability_gate)
    delivered = []
    seq.subscribe(delivered.append)
    seq.connect("c")
    m1 = seq.submit(_op("c", 1))
    fail["armed"] = True
    with pytest.raises(OSError):
        seq.submit(_op("c", 2))
    # fully unwound: same seq is re-assigned on retry, dedup floor intact
    assert seq.seq == m1.seq
    m2 = seq.submit(_op("c", 2))
    assert m2 is not None, "retry was swallowed as a duplicate"
    assert m2.seq == m1.seq + 1
    assert [m.seq for m in seq.log] == [1, 2, 3]
    assert [m.seq for m in delivered] == [1, 2, 3]


def test_sequencer_keeps_dedup_floor_when_later_subscriber_fails():
    """Asymmetry pin (review r1): a failure AFTER the durability gate
    leaves the op sequenced — the dedup floor must NOT roll back, or the
    caller's resend would double-sequence a durable op."""
    seq = Sequencer()
    durable = []
    seq.subscribe(lambda m: durable.append(m.seq))  # the durability gate
    fail = {"armed": False}

    def flaky_consumer(_m):
        if fail["armed"]:
            fail["armed"] = False
            raise RuntimeError("consumer died mid-delivery")

    seq.subscribe(flaky_consumer)
    seq.connect("c")
    seq.submit(_op("c", 1))
    fail["armed"] = True
    with pytest.raises(RuntimeError):
        seq.submit(_op("c", 2))
    # the op IS durable: the blind resend dedups instead of re-sequencing
    assert seq.submit(_op("c", 2)) is None
    assert [m.client_seq for m in seq.log
            if m.client_id == "c"] == [1, 2]
    assert seq.log[-1].seq == durable[-1]


def test_chaos_spec_rejects_wire_only_and_dirless_file_plans(tmp_path):
    """Plan validation (review r1): sites the in-process harness cannot
    fire fail LOUDLY instead of silently never firing and flunking the
    coverage oracle; file-level points require the durable dir."""
    wire_plan = FaultPlan(points=(
        FaultPoint("session.write", "stall", at=1),))
    with pytest.raises(ValueError, match="TCP stack"):
        run_chaos_load(ChaosLoadSpec(steps=8, plan=wire_plan,
                                     dir=str(tmp_path / "w")))
    flush_plan = FaultPlan(points=(
        FaultPoint("oplog.flush", "skip_fsync", at=1),))
    with pytest.raises(ValueError, match="durable tier"):
        run_chaos_load(ChaosLoadSpec(steps=8, plan=flush_plan, dir=None))


def test_scheduled_kill_of_last_live_shard_is_skipped_not_fatal():
    svc = ShardedOrderingService(
        n_shards=2, shard_ids=["sa", "sb"],
        faults=FaultInjector(FaultPlan(points=(
            FaultPoint("shard.kill", "kill", shard="sa", at=1),
            FaultPoint("shard.kill", "kill", shard="sb", at=2),
        ))))
    svc.create_document("d")
    svc.tick(1)
    assert svc.router.dead() == ["sa"]
    svc.tick(2)  # must NOT raise: sb is the last live shard
    assert svc.router.alive() == ["sb"]
    # the skipped kill is REPORTED unfired — the coverage oracle must
    # never claim a failover that did not happen (review r2)
    assert [p.shard for p in svc._faults.unfired()] == ["sb"]
    assert svc._faults.snapshot().get("shard.kill:kill") == 1


def test_plain_server_rejection_is_not_retried_or_masked():
    """Review r1: only transport-shaped RPC failures are retried — a
    deterministic server rejection (bad credentials) must surface
    immediately and typed, not burn the budget and come back as a
    ConnectionError."""
    server = _start_server(tenants={"t1": "secret"})
    from fluidframework_tpu.drivers.network_driver import _RpcClient

    rpc = _RpcClient("127.0.0.1", server.port,
                     retry=RetryPolicy(max_attempts=5, base_delay=0.01))
    try:
        before = rpc.retry_counters.get("retry.attempts")
        with pytest.raises(RpcError) as exc_info:
            rpc.request("auth", {"tenant": "t1", "secret": "wrong"})
        assert not isinstance(exc_info.value, ConnectionError)
        assert not isinstance(exc_info.value, RetryBudgetExhaustedError)
        # exactly one attempt: no retries burned on a deterministic no
        assert rpc.retry_counters.get("retry.attempts") == before + 1
        assert rpc.retry_counters.get("retry.retries") == 0
    finally:
        rpc.close()


def test_sequencer_join_and_leave_unwind_on_failed_stamp():
    seq = Sequencer()
    fail = {"armed": False}

    def durability_gate(msg):
        if fail["armed"]:
            fail["armed"] = False
            raise OSError("injected")

    seq.subscribe(durability_gate)
    fail["armed"] = True
    with pytest.raises(OSError):
        seq.connect("c")
    # not half-joined: the retry stamps a real JOIN
    conn = seq.connect("c")
    assert conn is not None
    assert seq.log[-1].type is MessageType.JOIN
    fail["armed"] = True
    with pytest.raises(OSError):
        seq.disconnect("c")
    assert seq.submit(_op("c", 1)) is not None  # still in the quorum
    seq.disconnect("c")
    assert seq.log[-1].type is MessageType.LEAVE


# --- summary storage seam -----------------------------------------------------


def _tree(text: bytes) -> SummaryTree:
    tree = SummaryTree()
    tree.add_blob("payload", text)
    sub = tree.add_tree("sub")
    sub.add_blob("x", b"x" + text)
    return tree


def test_summary_store_fault_leaves_no_visible_object(tmp_path):
    for kind in ("fail", "torn"):
        root = str(tmp_path / kind)
        plan = FaultPlan(points=(FaultPoint("storage.store", kind, at=1),))
        storage = FileSummaryStorage(root, faults=FaultInjector(plan))
        with pytest.raises(OSError):
            storage.upload("d", _tree(b"hello"), 1)
        # the upload never became visible: no commit, and a REOPEN (crash
        # shape) sweeps any torn tmp and serves nothing for the doc
        reopened = FileSummaryStorage(root)
        assert reopened.head("d") is None
        assert not [n for n in os.listdir(os.path.join(root, "objects"))
                    if ".tmp." in n]
        # the retry publishes cleanly on the reopened store
        reopened.upload("d", _tree(b"hello"), 1)
        tree, ref_seq = reopened.latest("d")
        assert ref_seq == 1
        assert tree.digest() == _tree(b"hello").digest()


def test_corrupt_summary_object_is_quarantined_not_served(tmp_path):
    root = str(tmp_path / "store")
    storage = FileSummaryStorage(root)
    handle = storage.upload("d", _tree(b"payload"), 1)
    objects = os.path.join(root, "objects")
    victim = os.path.join(objects, handle)
    # torn record: valid-JSON prefix impossible — and also a decodable
    # wrong-content case via a different object's bytes
    raw = open(victim, "rb").read()
    open(victim, "wb").write(raw[: len(raw) // 2])
    fresh = FileSummaryStorage(root)  # reopen: must not raise
    with pytest.raises(KeyError):
        fresh.read(handle)
    qdir = os.path.join(root, "quarantine")
    assert os.path.exists(os.path.join(qdir, handle))
    assert not os.path.exists(victim)
    # content-addressed heal: re-uploading republishes the object
    fresh2 = FileSummaryStorage(root)
    assert fresh2.upload("d", _tree(b"payload"), 2) == handle
    assert fresh2.read(handle).digest() == handle


def test_wrong_content_object_fails_the_checksum_gate(tmp_path):
    root = str(tmp_path / "store")
    storage = FileSummaryStorage(root)
    handle = storage.upload("d", _tree(b"one"), 1)
    other = SummaryTree()
    other.add_blob("payload", b"two")
    other_handle = storage.upload("d2", other, 1)
    objects = os.path.join(root, "objects")
    # swap contents: decodes fine, hashes to the WRONG digest
    blob_of = {}
    for h in (handle, other_handle):
        blob_of[h] = open(os.path.join(objects, h), "rb").read()
    open(os.path.join(objects, handle), "wb").write(blob_of[other_handle])
    fresh = FileSummaryStorage(root)
    with pytest.raises(KeyError):
        fresh.read(handle)
    assert os.path.exists(os.path.join(root, "quarantine", handle))


def test_stale_summary_read_serves_parent_and_load_converges(tmp_path):
    """A lagging replica serving an OLDER summary must only cost a longer
    tail replay — the loaded state still converges to the head."""
    plan = FaultPlan(points=(
        FaultPoint("storage.read", "stale", doc="doc", at=1),))
    injector = FaultInjector(plan)
    service = LocalOrderingService(
        storage=FileSummaryStorage(str(tmp_path / "s"), faults=injector))
    factory = LocalDocumentServiceFactory(service)
    loader = Loader(factory)

    def build(rt):
        rt.create_datastore("ds").create_channel("sequence-tpu", "text")

    c0 = loader.create("doc", "c0", build)
    text = c0.runtime.get_datastore("ds").get_channel("text")
    text.insert_text(0, "abcdef")
    c0.runtime.flush()
    c0.drain()
    # a NEWER summary exists now (service-side upload at the head)
    service.storage.upload("doc", c0.runtime.summarize(),
                           c0.runtime.ref_seq)
    text.insert_text(6, "XYZ")
    c0.runtime.flush()
    c0.drain()
    # this cold load's latest() is the doc's first — the stale serve
    # hands it the PARENT (attach) summary and it replays the whole tail
    late = loader.resolve("doc", "late")
    assert late.runtime.get_datastore("ds").get_channel("text").text \
        == "abcdefXYZ"
    assert injector.unfired() == []
    c0.drain()    # catch up on late's JOIN
    late.drain()
    assert late.runtime.summarize().digest() == \
        c0.runtime.summarize().digest()


# --- crash-point sweep (the durability oracle) --------------------------------


def test_oplog_crash_point_sweep_every_byte_of_last_record(tmp_path):
    """Truncate the op log at EVERY byte offset of the final record: the
    reopen must repair (losing at most that unacked record), never raise
    and never serve a torn record; appends must then resume cleanly."""
    path = tmp_path / "ops.jsonl"
    log = OpLog(str(path))
    for i in range(1, 5):
        log.append("d", _msg(i, contents={"payload": "x" * 20, "i": i}))
    log.close()
    data = path.read_bytes()
    assert data.endswith(b"\n")
    last_start = data[:-1].rfind(b"\n") + 1
    for cut in range(last_start, len(data)):
        case = tmp_path / f"case{cut}.jsonl"
        case.write_bytes(data[:cut])
        reopened = OpLog(str(case))
        seqs = [m.seq for m in reopened.get("d")]
        if cut == len(data) - 1:
            # complete record, torn newline: sealed, nothing lost
            assert seqs == [1, 2, 3, 4]
        else:
            assert seqs == [1, 2, 3], f"cut={cut}: {seqs}"
        head = reopened.head("d")
        reopened.append("d", _msg(head + 1))
        reopened.close()
        final = OpLog(str(case))
        assert [m.seq for m in final.get("d")] == \
            list(range(1, head + 2)), f"cut={cut}"
        final.close()


def test_summary_upload_crash_sweep_at_every_fault_point(tmp_path):
    """Inject a store failure at EVERY object-write occurrence of one
    summary upload, in both shapes (clean fail, torn tmp): the reopened
    store must never raise, never serve a partial summary, and a retry
    must publish the identical tree."""
    tree = _tree(b"sweep")
    probe = FileSummaryStorage(str(tmp_path / "probe"))
    probe.upload("d", tree, 1)
    n_writes = len(os.listdir(os.path.join(str(tmp_path / "probe"),
                                           "objects")))
    assert n_writes >= 3  # root tree + subtree + blobs
    for occurrence in range(1, n_writes + 1):
        for kind in ("fail", "torn"):
            root = str(tmp_path / f"s{occurrence}-{kind}")
            plan = FaultPlan(points=(
                FaultPoint("storage.store", kind, at=occurrence),))
            storage = FileSummaryStorage(root,
                                         faults=FaultInjector(plan))
            with pytest.raises(OSError):
                storage.upload("d", _tree(b"sweep"), 1)
            reopened = FileSummaryStorage(root)  # crash shape: no raise
            assert reopened.head("d") is None    # partial upload invisible
            handle = reopened.upload("d", _tree(b"sweep"), 1)
            got, ref_seq = reopened.latest("d")
            assert (got.digest(), ref_seq) == (tree.digest(), 1)
            # every published object passes the checksum gate cold
            cold = FileSummaryStorage(root)
            assert cold.read(handle).digest() == handle


# --- DeltaManager: retry + fence self-heal ------------------------------------


def test_delta_manager_submit_retries_through_transient_append_faults():
    plan = FaultPlan(points=(
        FaultPoint("oplog.append", "fail", at=2, count=2),))
    service = LocalOrderingService(oplog=OpLog(faults=FaultInjector(plan)))
    factory = LocalDocumentServiceFactory(service)
    clock = VirtualClock()
    loader = Loader(factory, clock=clock,
                    retry=RetryPolicy(max_attempts=5, base_delay=0.01))

    def build(rt):
        rt.create_datastore("ds").create_channel("sequence-tpu", "text")

    c = loader.create("doc", "c0", build)
    text = c.runtime.get_datastore("ds").get_channel("text")
    text.insert_text(0, "hi")   # this submit hits the 2-append outage
    c.runtime.flush()
    c.drain()
    assert text.text == "hi"
    assert c.runtime.ref_seq == service.oplog.head("doc")
    retries = c.delta_manager.retry_counters
    assert retries.get("retry.retries") >= 1
    assert retries.get("retry.exhausted") == 0


def test_delta_manager_connect_budget_exhaustion_is_typed():
    plan = FaultPlan(points=(
        FaultPoint("oplog.append", "fail", at=1, count=1000),))
    service = LocalOrderingService(oplog=OpLog(faults=FaultInjector(plan)))
    factory = LocalDocumentServiceFactory(service)
    endpoint = factory.create_document("doc", ContainerRuntime().summarize())
    dm = DeltaManager(endpoint, clock=VirtualClock(),
                      retry=RetryPolicy(max_attempts=3, base_delay=0.01))
    with pytest.raises(RetryBudgetExhaustedError):
        dm.connect("c0")
    assert dm.retry_counters.get("retry.exhausted") == 1
    assert dm.retry_counters.get("retry.attempts") == 3  # bounded


def test_fenced_mid_burst_client_converges_without_host_polling():
    """ISSUE 9 satellite: ``fence_required`` used to be poll-only — the
    HOST had to notice and reconnect.  Now the container's own drain()
    self-heals: the DeltaManager re-resolves the recovered owner through
    its factory resolver and replays the held outbound ops itself."""
    service = ShardedOrderingService(n_shards=4)
    factory = LocalDocumentServiceFactory(service)
    loader = Loader(factory, clock=VirtualClock(),
                    retry=RetryPolicy(max_attempts=4, base_delay=0.01))

    def build(rt):
        rt.create_datastore("ds").create_channel("sequence-tpu", "text")

    c = loader.create("doc", "c0", build)
    text = c.runtime.get_datastore("ds").get_channel("text")
    text.insert_text(0, "before")
    c.runtime.flush()
    c.drain()
    service.kill_shard(service.shard_of("doc"))
    # mid-burst edits: submits hit the fence; the wire-drain swallows the
    # ConnectionError and the ops stay queued
    text.insert_text(6, "-after")
    c.runtime.flush()
    assert c.delta_manager.fence_required
    # host does NOTHING but pump drain(): no flag polling, no explicit
    # factory.resolve — the manager heals itself
    for _ in range(8):
        c.drain()
        c.runtime.flush()
        if c.runtime.ref_seq == service.oplog.head("doc") \
                and not c.runtime._pending_wire and not c.runtime._outbox:
            break
    assert not c.delta_manager.fence_required
    assert text.text == "before-after"
    assert c.runtime.ref_seq == service.oplog.head("doc")
    # a second (never-fenced) load sees the identical state
    check = loader.resolve("doc")
    assert check.runtime.get_datastore("ds").get_channel("text").text \
        == "before-after"


# --- server admission control -------------------------------------------------


def test_catchup_admission_sheds_overload_with_typed_nack(monkeypatch):
    from fluidframework_tpu.utils.telemetry import (ConfigProvider,
                                                    MonitoringContext)

    service = LocalOrderingService()
    # Result cache off: every request takes the FOLD lane (the warm
    # priority lane would otherwise serve this test's empty doc set
    # without ever consulting admission — pinned separately in
    # tests/test_catchup_storm.py).
    server = OrderingServer(
        service, catchup_max_inflight=1,
        mc=MonitoringContext(config=ConfigProvider(
            {"Catchup.Cache": "off"})))
    entered = threading.Event()
    release = threading.Event()

    def slow_catchup(self, session, params, **kw):
        entered.set()
        assert release.wait(timeout=30)
        return {"docs": {}}

    monkeypatch.setattr(OrderingServer, "_catchup_rpc", slow_catchup)

    class _Session:
        tenant = None

    results = {}

    def first():
        results["first"] = server._dispatch(_Session(), "catchup", {})

    t = threading.Thread(target=first)
    t.start()
    assert entered.wait(timeout=30)
    with pytest.raises(NackError) as exc_info:
        server._dispatch(_Session(), "catchup", {})
    assert exc_info.value.code == "overloaded"
    assert exc_info.value.retry_after > 0
    # the durable-log path still serves while the fold tier is saturated
    service.create_document("d")
    ep = service.endpoint("d")
    ep.connect("c")
    ep.submit(_op("c", 1))
    assert server._dispatch(_Session(), "deltas", {"doc": "d"}) != []
    release.set()
    t.join(timeout=30)
    assert results["first"] == {"docs": {}}
    assert server.admission.get("catchup.admitted") == 1
    assert server.admission.get("catchup.shed") == 1
    # the slot was released: a fresh request admits again
    release.set()
    assert server._dispatch(_Session(), "catchup", {}) == {"docs": {}}
    assert server.admission.get("catchup.admitted") == 2


# --- the chaos acceptance oracle ----------------------------------------------


@pytest.mark.parametrize("seed", [0, 3, 11])
def test_chaos_load_byte_identical_to_fault_free_oracle(seed, tmp_path):
    """THE acceptance gate: a generated schedule of ≥5 fault kinds
    (durable-append failure, torn append, stale summary serve, shard
    kill, stalled client) against 4 shards; final per-doc summaries must
    be byte-identical to the fault-free single-shard oracle, every
    injected fault observed, and no retry budget exceeded."""
    spec = ChaosLoadSpec(seed=seed, shards=4, docs=6, clients_per_doc=2,
                         steps=160, dir=str(tmp_path / "chaos"))
    chaos, oracle = run_chaos_with_oracle(spec)
    assert chaos.per_doc_digest == oracle.per_doc_digest
    assert chaos.per_doc_head == oracle.per_doc_head
    assert chaos.unfired == [], "plan points that never exercised"
    kinds = {k.split(":", 1) [0] for k in chaos.fault_counts}
    assert {"oplog.append", "storage.read", "shard.kill",
            "client.stall"} <= kinds
    assert len(chaos.fault_counts) >= 5  # distinct site:kind classes
    assert chaos.kills and chaos.kills[0][2], "the kill fenced no docs"
    assert chaos.recovery_ticks, "no recovery latency was measured"


def test_chaos_replay_is_bit_identical(tmp_path):
    """The same (seed, plan) must replay to IDENTICAL telemetry: fault
    observation counters, retry counters, digests, and heads."""
    runs = []
    for i in range(2):
        spec = ChaosLoadSpec(seed=11, steps=160,
                             dir=str(tmp_path / f"run{i}"))
        runs.append(run_chaos_load(spec))
    a, b = runs
    assert a.fault_counts == b.fault_counts
    assert a.retry_counts == b.retry_counts
    assert a.per_doc_digest == b.per_doc_digest
    assert a.per_doc_head == b.per_doc_head
    assert a.recovery_ticks == b.recovery_ticks


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(16)))
def test_chaos_matrix_wide_seed_sweep(seed, tmp_path):
    """Nightly-scale matrix: 16 seeds of generated chaos, each against
    its oracle twin (the tier-1 subset covers 3 seeds)."""
    spec = ChaosLoadSpec(seed=seed, shards=4, docs=8, clients_per_doc=2,
                         steps=240, dir=str(tmp_path / "chaos"))
    chaos, oracle = run_chaos_with_oracle(spec)
    assert chaos.per_doc_digest == oracle.per_doc_digest
    assert chaos.per_doc_head == oracle.per_doc_head
    assert chaos.unfired == []


# --- faults over the wire -----------------------------------------------------


def _start_server(service=None, faults=None, **kw):
    server = OrderingServer(service or LocalOrderingService(), port=0,
                            faults=faults, **kw)
    server.start_in_thread()
    return server


def test_rpc_send_failures_are_retried_transparently():
    server = _start_server()
    plan = FaultPlan(points=(
        FaultPoint("rpc.send", "fail", at=3, count=2),))
    injector = FaultInjector(plan)
    factory = NetworkDocumentServiceFactory(
        port=server.port, faults=injector,
        retry=RetryPolicy(max_attempts=4, base_delay=0.01))
    try:
        runtime = ContainerRuntime()
        runtime.create_datastore("ds")
        doc = factory.create_document("net", runtime.summarize())
        conn = doc.connection()
        conn.connect("cA")
        ref = conn.head_seq
        for i in range(4):
            ref = conn.submit(_op("cA", i + 1, ref_seq=ref)).seq
        assert injector.unfired() == []
        assert factory._rpc.retry_counters.get("retry.retries") >= 1
        assert factory._rpc.retry_counters.get("retry.exhausted") == 0
        assert conn.head_seq == ref  # nothing lost, nothing doubled
    finally:
        factory.close()


def test_rpc_recv_duplicate_and_delay_converge_via_watermarks():
    """Duplicate delivery dedups at the watermark; a one-frame reorder
    parks and repairs — the client's final view matches the log."""
    server = _start_server()
    setup = NetworkDocumentServiceFactory(port=server.port)
    plan = FaultPlan(points=(
        # doc-scoped: count ONLY this doc's broadcast frames at client B
        FaultPoint("rpc.recv", "duplicate", doc="net", at=2),
        FaultPoint("rpc.recv", "delay", doc="net", at=4),
    ))
    injector = FaultInjector(plan)
    watcher = NetworkDocumentServiceFactory(port=server.port,
                                            faults=injector)
    try:
        runtime = ContainerRuntime()
        runtime.create_datastore("ds")
        doc_a = setup.create_document("net", runtime.summarize())
        conn_a = doc_a.connection()
        conn_a.connect("cA")

        service_b = watcher.resolve("net")
        dm = DeltaManager(service_b)
        dm.connect("cB")
        dm.note_delivered(service_b.delta_storage.head())
        got = []
        dm.subscribe(lambda m: got.append(m.seq))

        ref = conn_a.head_seq
        for i in range(6):
            ref = conn_a.submit(_op("cA", i + 1, ref_seq=ref)).seq
        deadline = time.time() + 10
        while time.time() < deadline and dm.last_delivered_seq < ref:
            time.sleep(0.02)
        assert dm.last_delivered_seq == ref
        assert got == sorted(set(got)), "duplicate or disorder leaked"
        assert injector.unfired() == []
    finally:
        watcher.close()
        setup.close()


def test_rpc_disconnect_mid_burst_reconnects_and_converges():
    """An injected RPC disconnect mid-burst kills the shared socket; the
    client rebuilds its connection (fresh factory, fresh client id — the
    crash-resume identity path) and the container's resubmit machinery
    replays the held ops: nothing is lost, nothing doubles, and a fresh
    load sees exactly the converged state."""
    server = _start_server()
    plan = FaultPlan(points=(
        FaultPoint("rpc.send", "disconnect", doc="net", at=8),))
    injector = FaultInjector(plan)
    factory = NetworkDocumentServiceFactory(port=server.port,
                                            faults=injector)
    loader = Loader(factory)

    def build(rt):
        rt.create_datastore("ds").create_channel("sequence-tpu", "text")

    c = loader.create("net", "c0", build)
    text = c.runtime.get_datastore("ds").get_channel("text")
    for i in range(10):
        # Once the injected disconnect kills the socket, every flush's
        # ConnectionLostError is a ConnectionError: the wire-drain keeps
        # the encoded ops QUEUED (optimistic text intact) — no crash,
        # no loss, exactly the offline contract.
        text.insert_text(len(text.text), f"w{i}.")
        c.runtime.flush()
        c.drain()
    assert injector.unfired() == [], "the injected disconnect never fired"
    assert c.runtime._pending_wire, "no ops were left queued by the death"
    # more offline edits pile into the pending queue
    text.insert_text(len(text.text), "offline.")
    # wait until the server reaps the dead session (EOF → LEAVE) before
    # the same client identity rejoins: rejoining earlier would resume
    # the doomed record and the late LEAVE would evict the live client
    deadline = time.time() + 10
    while time.time() < deadline and server.service \
            .endpoint("net")._orderer.sequencer.is_connected("c0"):
        time.sleep(0.02)
    assert not server.service.endpoint("net") \
        ._orderer.sequencer.is_connected("c0")
    # rebuild the transport; catch-up acks the ops that DID land before
    # the death, resubmit re-issues the rest
    factory2 = NetworkDocumentServiceFactory(port=server.port)
    try:
        c.reconnect(document_service=factory2.resolve("net"))
        deadline = time.time() + 10
        while time.time() < deadline:
            c.runtime.flush()
            c.drain()
            head = factory2.resolve("net").delta_storage.head()
            if c.runtime.ref_seq == head and not c.runtime._pending_wire \
                    and not c.runtime._outbox:
                break
            time.sleep(0.02)
        expected = "".join(f"w{i}." for i in range(10)) + "offline."
        # every edit survived the disconnect, exactly once, in order
        assert text.text == expected
        fresh = Loader(factory2).resolve("net")
        assert fresh.runtime.get_datastore("ds") \
            .get_channel("text").text == expected
    finally:
        factory2.close()
        factory.close()


def test_stalled_session_is_demoted_and_backfills():
    """The ``session.write`` stall: the broadcaster demotes the stalled
    sink instead of stalling the shard, the client gets the demotion
    notice, re-subscribes, and backfills the dropped span from the
    durable log."""
    plan = FaultPlan(points=(
        FaultPoint("session.write", "stall", at=2, count=3),))
    injector = FaultInjector(plan)
    server = _start_server(faults=injector)
    factory = NetworkDocumentServiceFactory(port=server.port)
    try:
        runtime = ContainerRuntime()
        runtime.create_datastore("ds")
        doc = factory.create_document("net", runtime.summarize())
        conn = doc.connection()
        dm = DeltaManager(factory.resolve("net"))
        dm.connect("cA")
        got = []
        dm.subscribe(lambda m: got.append(m.seq))
        ref = conn.head_seq
        dm.note_delivered(ref)
        for i in range(8):
            ref = conn.submit(_op("cA", i + 1, ref_seq=ref)).seq
        deadline = time.time() + 10
        while time.time() < deadline and dm.last_delivered_seq < ref:
            time.sleep(0.02)
        assert dm.last_delivered_seq == ref, "backfill never completed"
        assert conn.demotions_seen >= 1
        assert injector.unfired() == []
        assert server.broadcaster.counters.get("demotions") >= 1
    finally:
        factory.close()
