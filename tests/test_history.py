"""Summary commit/ref history chain (Historian/gitrest capability):
git-style commits over summary trees, named refs, history walk, file
persistence, and commit digests stamped into scribe acks."""

from fluidframework_tpu.drivers.file_driver import FileSummaryStorage
from fluidframework_tpu.protocol.messages import MessageType, RawOperation
from fluidframework_tpu.protocol.summary import (
    SummaryStorage,
    SummaryTree,
)
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.runtime.summarizer import (
    SummarizerOptions,
    SummaryManager,
)
from fluidframework_tpu.service import LocalOrderingService


def _tree(text: str) -> SummaryTree:
    tree = SummaryTree()
    tree.add_blob("content", text.encode("utf-8"))
    return tree


def _fill(storage, doc="doc"):
    handles = []
    for i, word in enumerate(["one", "two", "three"]):
        handles.append(
            storage.upload(doc, _tree(word), ref_seq=10 * (i + 1),
                           message=f"summary {word}")
        )
    return handles


def test_commit_chain_walk():
    storage = SummaryStorage()
    handles = _fill(storage)

    commits = storage.history("doc")
    assert len(commits) == 3
    # newest-first, trees match upload order reversed
    assert [c.tree for c in commits] == list(reversed(handles))
    assert [c.ref_seq for c in commits] == [30, 20, 10]
    # parent pointers chain, root commit has none
    assert commits[0].parent == commits[1].digest()
    assert commits[1].parent == commits[2].digest()
    assert commits[2].parent is None
    # head is the newest commit
    assert storage.head("doc") == commits[0].digest()
    # checkout agrees with latest()
    tree, seq = storage.checkout("doc")
    latest_tree, latest_seq = storage.latest("doc")
    assert (tree.digest(), seq) == (latest_tree.digest(), latest_seq)
    # commit_for resolves (tree, ref_seq) to its commit
    assert storage.commit_for("doc", handles[1], 20) == commits[1].digest()
    assert storage.commit_for("doc", handles[1], 999) is None
    assert storage.commit_for("doc", "nope", 10) is None
    # identical trees uploaded at two sequence points resolve separately
    dup = storage.upload("doc", _tree("three"), ref_seq=40)
    assert dup == handles[2]  # content-addressed: same tree handle
    assert storage.commit_for("doc", dup, 40) != \
        storage.commit_for("doc", dup, 30)


def test_named_refs_pin_old_commits():
    storage = SummaryStorage()
    _fill(storage)
    commits = storage.history("doc")
    storage.create_ref("doc", "v1", commits[-1].digest())

    assert set(storage.refs("doc")) == {"main", "v1"}
    tree, seq = storage.checkout("doc", ref="v1")
    assert seq == 10
    assert tree.blob_bytes("content") == b"one"
    # history from the pinned ref sees only the prefix
    assert [c.ref_seq for c in storage.history("doc", ref="v1")] == [10]


def test_history_limit():
    storage = SummaryStorage()
    _fill(storage)
    assert [c.ref_seq for c in storage.history("doc", limit=2)] == [30, 20]


def test_file_storage_history_survives_reopen(tmp_path):
    root = str(tmp_path / "store")
    storage = FileSummaryStorage(root)
    _fill(storage)
    commits = storage.history("doc")
    storage.create_ref("doc", "release", commits[1].digest())

    reopened = FileSummaryStorage(root)
    recommits = reopened.history("doc")
    assert [c.digest() for c in recommits] == [c.digest() for c in commits]
    assert [c.message for c in recommits] == [
        "summary three", "summary two", "summary one"
    ]
    assert reopened.refs("doc") == storage.refs("doc")
    tree, seq = reopened.checkout("doc", ref="release")
    assert seq == 20
    assert tree.blob_bytes("content") == b"two"


def test_scribe_ack_carries_commit_digest():
    service = LocalOrderingService()
    ep = service.create_document("doc")
    runtime = ContainerRuntime()
    ds = runtime.create_datastore("ds")
    text = ds.create_channel("sequence-tpu", "text")
    runtime.connect(ep, "a")
    runtime.drain()
    mgr = SummaryManager(runtime, service.storage, "doc",
                         SummarizerOptions(ops_per_summary=1000))
    text.insert_text(0, "hello")
    runtime.drain()
    mgr.summarize_now()
    runtime.drain()

    acks = [m for m in ep.log if m.type is MessageType.SUMMARY_ACK]
    assert len(acks) == 1
    commit_digest = acks[0].contents["commit"]
    commit = service.storage.read_commit(commit_digest)
    assert commit.tree == acks[0].contents["handle"]
    assert service.storage.head("doc") == commit_digest


def test_unknown_ref_target_rejected():
    storage = SummaryStorage()
    _fill(storage)
    try:
        storage.create_ref("doc", "bad", "not-a-commit")
    except KeyError:
        pass
    else:
        raise AssertionError("create_ref accepted an unknown commit")


def test_torn_store_reopens_without_dangling_refs(tmp_path):
    import json
    import os

    root = str(tmp_path / "store")
    storage = FileSummaryStorage(root)
    _fill(storage)
    commits = storage.history("doc")
    storage.create_ref("doc", "ok", commits[0].digest())
    # simulate a torn write: a pin whose commit record was lost
    with open(os.path.join(root, "refs.jsonl"), "a", encoding="utf-8") as f:
        f.write(json.dumps(
            {"doc": "doc", "ref": "lost", "commit": "f" * 64}) + "\n")

    reopened = FileSummaryStorage(root)  # must not raise
    assert "lost" not in reopened.refs("doc")
    assert reopened.refs("doc")["ok"] == commits[0].digest()


def test_torn_trailing_line_reopens_losing_only_last_record(tmp_path):
    """A crash mid-append leaves a PARTIAL final line; the store must
    reopen losing only that record (ADVICE r3), while a torn line earlier
    in the file still raises (corruption, not a torn append)."""
    import json
    import os

    import pytest

    root = str(tmp_path / "store")
    storage = FileSummaryStorage(root)
    _fill(storage)
    commits = storage.history("doc")
    path = os.path.join(root, "commits.jsonl")
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"doc": "doc", "tree": "abc123", "trunca')  # no newline

    reopened = FileSummaryStorage(root)  # must not raise
    assert [c.digest() for c in reopened.history("doc")] == \
        [c.digest() for c in commits]

    # CRITICAL: reopen must have REPAIRED the torn tail, so an append
    # cannot merge onto the partial line — the appended commit must
    # survive the next reopen (review r4: without repair the ack'd
    # upload silently vanished and a second append corrupted the store).
    from fluidframework_tpu.protocol.summary import SummaryBlob, SummaryTree
    tree = SummaryTree(children={"post": SummaryBlob(b"post-crash")})
    reopened.upload("doc", tree, ref_seq=99)
    reopened2 = FileSummaryStorage(root)
    assert len(reopened2.history("doc")) == len(commits) + 1
    assert reopened2.latest("doc")[0].digest() == tree.digest()

    # a torn MIDDLE line is corruption and must still fail loudly
    with open(path, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    lines.insert(1, '{"torn": tru')
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(json.JSONDecodeError):
        FileSummaryStorage(root)


def test_oplog_torn_tail_reopens_and_appends_durably(tmp_path):
    """The op log (highest write rate in the store) gets the same torn-
    tail repair: reopen loses only the unacked final record, and the next
    append lands on a clean line."""
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )
    from fluidframework_tpu.service.oplog import OpLog

    path = str(tmp_path / "ops.jsonl")

    def op(seq):
        return SequencedMessage(
            seq=seq, client_id="c0", client_seq=seq, ref_seq=seq - 1,
            min_seq=0, type=MessageType.OP, contents={"n": seq},
        )

    log = OpLog(path)
    for seq in (1, 2, 3):
        log.append("doc", op(seq))
    log.flush()
    log.close() if hasattr(log, "close") else None
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"doc": "doc", "msg": {"se')  # crash mid-append

    log2 = OpLog(path)  # must not raise; torn record dropped
    assert [m.seq for m in log2.get("doc")] == [1, 2, 3]
    log2.append("doc", op(4))
    log2.flush()

    log3 = OpLog(path)
    assert [m.seq for m in log3.get("doc")] == [1, 2, 3, 4]


def test_corrupt_chain_reports_missing_commit():
    import pytest

    storage = SummaryStorage()
    _fill(storage)
    head = storage.head("doc")
    # sever the chain below the head
    parent = storage.read_commit(head).parent
    del storage._commit_objects[parent]
    with pytest.raises(ValueError, match="corrupt commit chain"):
        storage.history("doc")


def test_old_format_commit_records_still_load(tmp_path):
    import json
    import os

    root = str(tmp_path / "store")
    storage = FileSummaryStorage(root)
    handles = _fill(storage)
    # rewrite commits.jsonl in the old (parent-less) format
    with open(os.path.join(root, "commits.jsonl"), "w",
              encoding="utf-8") as f:
        for handle, seq in zip(handles, (10, 20, 30)):
            f.write(json.dumps(
                {"doc": "doc", "handle": handle, "refSeq": seq}) + "\n")
    reopened = FileSummaryStorage(root)
    commits = reopened.history("doc")
    assert [c.tree for c in commits] == list(reversed(handles))
    assert commits[2].parent is None
    tree, seq = reopened.latest("doc", at_or_below=25)
    assert (tree.blob_bytes("content"), seq) == (b"two", 20)


def test_cross_document_ref_rejected():
    import pytest

    storage = SummaryStorage()
    _fill(storage, doc="docA")
    _fill(storage, doc="docB")
    with pytest.raises(ValueError, match="belongs to document"):
        storage.create_ref("docA", "v1", storage.head("docB"))


def test_history_limit_skips_truncated_tail():
    storage = SummaryStorage()
    _fill(storage)
    commits = storage.history("doc")
    # sever the oldest link; a limited walk that never reaches it succeeds
    del storage._commit_objects[commits[2].digest()]
    assert [c.ref_seq for c in storage.history("doc", limit=2)] == [30, 20]
    assert storage.history("doc", limit=0) == []


def test_history_cli_respects_to_seq(tmp_path):
    import json
    import subprocess
    import sys

    root = str(tmp_path / "store")
    storage = FileSummaryStorage(root)
    _fill(storage)
    out = subprocess.run(
        [sys.executable, "-m", "fluidframework_tpu.tools.replay",
         root, "doc", "--history", "--json", "--to-seq", "25"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert [r["refSeq"] for r in json.loads(out.stdout)] == [20, 10]


def test_main_cannot_be_repointed():
    storage = SummaryStorage()
    _fill(storage)
    commits = storage.history("doc")
    try:
        storage.create_ref("doc", "main", commits[-1].digest())
    except ValueError:
        pass
    else:
        raise AssertionError("create_ref repointed main")
