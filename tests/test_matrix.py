"""SharedMatrix: permutation-vector merges, cell LWW/FWW, canonical summaries."""

import pytest

from fluidframework_tpu.dds import SharedMatrix
from fluidframework_tpu.testing import MockContainerRuntimeFactory


def make_pair():
    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(SharedMatrix("m"))
    b = factory.create_client("B").attach(SharedMatrix("m"))
    return factory, a, b


def seeded(factory, a, rows=3, cols=3):
    a.insert_rows(0, rows)
    a.insert_cols(0, cols)
    factory.process_all_messages()


def assert_converged(*replicas):
    digests = {r.summarize().digest() for r in replicas}
    assert len(digests) == 1, [r.to_list() for r in replicas]


def test_basic_grid_and_cells():
    factory, a, b = make_pair()
    seeded(factory, a)
    assert (a.row_count, a.col_count) == (3, 3) == (b.row_count, b.col_count)
    a.set_cell(1, 2, "x")
    assert a.get_cell(1, 2) == "x"  # optimistic local read
    factory.process_all_messages()
    assert b.get_cell(1, 2) == "x"
    assert_converged(a, b)


def test_concurrent_row_insert_converges():
    factory, a, b = make_pair()
    seeded(factory, a, rows=2, cols=1)
    a.set_cell(0, 0, "r0")
    a.set_cell(1, 0, "r1")
    factory.process_all_messages()
    # Both insert a row at position 1 concurrently.
    a.insert_rows(1, 1)
    b.insert_rows(1, 1)
    factory.process_all_messages()
    assert a.row_count == b.row_count == 4
    # Cells ride their handles: r0 still first, r1 now last.
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "r0"
    assert a.get_cell(3, 0) == b.get_cell(3, 0) == "r1"
    assert_converged(a, b)


def test_cell_write_survives_concurrent_row_move():
    factory, a, b = make_pair()
    seeded(factory, a, rows=3, cols=1)
    # A writes to row 2 while B concurrently inserts a row above it: the
    # write lands on the same logical row (handle), now at position 3.
    a.set_cell(2, 0, "target")
    b.insert_rows(0, 1)
    factory.process_all_messages()
    assert a.get_cell(3, 0) == b.get_cell(3, 0) == "target"
    assert_converged(a, b)


def test_remove_rows_drops_cells():
    factory, a, b = make_pair()
    seeded(factory, a, rows=3, cols=2)
    a.set_cell(1, 0, "doomed")
    a.set_cell(2, 1, "keep")
    factory.process_all_messages()
    b.remove_rows(1, 1)
    factory.process_all_messages()
    assert a.row_count == b.row_count == 2
    assert a.get_cell(1, 1) == b.get_cell(1, 1) == "keep"
    factory.advance_min_seq()  # expire the tombstone; cells collected
    assert_converged(a, b)
    assert len(a._cells) == len(b._cells) == 1


def test_concurrent_cell_set_lww():
    factory, a, b = make_pair()
    seeded(factory, a)
    a.set_cell(0, 0, "fromA")
    b.set_cell(0, 0, "fromB")  # sequenced second → wins under LWW
    factory.process_all_messages()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "fromB"
    assert_converged(a, b)


def test_fww_first_sequenced_writer_wins():
    factory, a, b = make_pair()
    seeded(factory, a)
    a.switch_policy("fww")
    factory.process_all_messages()
    a.set_cell(0, 0, "fromA")  # sequenced first → keeps the cell
    b.set_cell(0, 0, "fromB")
    factory.process_all_messages()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "fromA"
    assert_converged(a, b)


def test_fww_overwrite_after_seeing_winner_is_allowed():
    factory, a, b = make_pair()
    seeded(factory, a)
    a.switch_policy("fww")
    a.set_cell(0, 0, "first")
    factory.process_all_messages()
    b.set_cell(0, 0, "second")  # B saw "first" (ref_seq past it) → allowed
    factory.process_all_messages()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "second"
    assert_converged(a, b)


def test_fww_same_client_back_to_back_allowed():
    factory, a, b = make_pair()
    seeded(factory, a)
    a.switch_policy("fww")
    factory.process_all_messages()
    a.set_cell(0, 0, "v1")
    a.set_cell(0, 0, "v2")  # same client: not a conflict
    factory.process_all_messages()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "v2"
    assert_converged(a, b)


def test_pending_local_read_until_ack():
    factory, a, b = make_pair()
    seeded(factory, a)
    b.set_cell(0, 0, "remote")
    factory.process_all_messages()
    a.set_cell(0, 0, "mine")
    assert a.get_cell(0, 0) == "mine"
    factory.process_all_messages()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "mine"


def test_summary_roundtrip():
    factory, a, b = make_pair()
    seeded(factory, a)
    a.set_cell(0, 0, 1)
    b.set_cell(2, 2, 2)
    a.remove_cols(1, 1)
    factory.process_all_messages()
    summary = a.summarize()
    c = SharedMatrix("m2")
    c.load(summary)
    assert c.row_count == 3 and c.col_count == 2
    assert c.summarize().digest() == summary.digest()
    assert c.to_list() == a.to_list()


def test_summary_identical_across_replicas_despite_local_handles():
    factory, a, b = make_pair()
    seeded(factory, a, rows=2, cols=2)
    # Interleave structural edits from both replicas so their local handle
    # allocation orders differ.
    a.insert_rows(0, 1)
    b.insert_cols(1, 1)
    factory.process_all_messages()
    b.remove_rows(1, 1)
    a.set_cell(0, 0, "z")
    factory.process_all_messages()
    assert_converged(a, b)


def test_out_of_range_raises():
    factory, a, b = make_pair()
    seeded(factory, a, rows=1, cols=1)
    with pytest.raises(IndexError):
        a.set_cell(5, 0, "nope")
    with pytest.raises(IndexError):
        a.get_cell(0, 9)


def test_detached_then_summary():
    m = SharedMatrix("solo")
    m.insert_rows(0, 2)
    m.insert_cols(0, 2)
    m.set_cell(0, 1, 42)
    summary = m.summarize()
    m2 = SharedMatrix("solo2")
    m2.load(summary)
    assert m2.get_cell(0, 1) == 42
    assert m2.summarize().digest() == summary.digest()


def test_fww_switch_takes_effect_at_sequence_position():
    # Review-found race: two concurrent setCells sequence BEFORE the
    # setPolicy op does; every replica (including the switcher) must judge
    # them under LWW.
    factory, a, b = make_pair()
    seeded(factory, a)
    b.set_cell(0, 0, "Bval")
    a.set_cell(0, 0, "Aval")
    a.switch_policy("fww")
    factory.process_all_messages()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "Aval"
    assert_converged(a, b)
    # After the switch is sequenced, FWW applies everywhere.
    a.set_cell(1, 1, "first")
    b.set_cell(1, 1, "second")
    factory.process_all_messages()
    assert a.get_cell(1, 1) == b.get_cell(1, 1) == "first"
    assert_converged(a, b)
