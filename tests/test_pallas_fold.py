"""Exact parity: the Pallas VMEM-resident fold vs the canonical scan.

The Pallas kernel is a Mosaic-conservative restatement of the scan step
(rolls instead of gathers, reduction searches, ladder prefix sums); these
tests pin it to ``replay_vmapped`` ARRAY-FOR-ARRAY on the bench workload,
the dryrun's hard-semantics docs (deep-lag obliterate, overlap removers,
annotate races, warm obliterate base), and fuzz logs.  Interpret mode —
runs on any backend, so CI covers the port's semantics; Mosaic compilation
is exercised on real TPU behind FF_PALLAS_FOLD."""

import jax
import numpy as np
import pytest

import bench
from fluidframework_tpu.ops.mergetree_kernel import (
    pack_mergetree_batch,
    replay_vmapped,
    summaries_from_export,
    _export_state,
)
from fluidframework_tpu.ops.pallas_fold import replay_vmapped_pallas


def _assert_states_equal(a, b, n_docs):
    for field in a._fields:
        av, bv = np.asarray(getattr(a, field)), np.asarray(getattr(b, field))
        assert av.shape == bv.shape, field
        if field in ("n", "overflow"):
            np.testing.assert_array_equal(av, bv, err_msg=field)
            continue
        # Only slots [0, n) are meaningful; the scan and the kernel may
        # differ in dead-slot garbage above n after shifts.
        for d in range(n_docs):
            nd = int(np.asarray(a.n)[d])
            np.testing.assert_array_equal(
                av[d, :nd], bv[d, :nd], err_msg=f"{field} doc {d}"
            )


def _parity(docs):
    state, ops, meta = pack_mergetree_batch(docs)
    final_scan = jax.jit(replay_vmapped)(state, ops)
    final_pallas = replay_vmapped_pallas(state, ops, interpret=True)
    _assert_states_equal(final_scan, final_pallas, len(docs))
    return final_pallas, meta


def test_pallas_fold_matches_scan_on_bench_workload():
    docs = [bench.synth_doc(i, 48) for i in range(24)]
    final, meta = _parity(docs)
    # and byte-identical summaries through the export + extraction path
    # (same flags replay_export derives from the packed meta)
    import jax.numpy as jnp

    from fluidframework_tpu.ops.mergetree_kernel import (
        _export_flags,
        export_to_numpy,
    )

    i16, ob_rows, ov_rows, i8, props_rows = _export_flags(meta)
    doc_base = jnp.asarray(meta["doc_base"]) if i16 else \
        jnp.zeros((len(docs),), jnp.int32)
    export = export_to_numpy(
        _export_state(final, doc_base, i16, ob_rows, ov_rows, i8,
                      props_rows=props_rows))
    summaries = summaries_from_export(meta, export)
    for doc, summary in zip(docs[:6], summaries[:6]):
        assert summary.digest() == \
            bench.oracle_replay(doc).summarize().digest(), doc.doc_id


def test_pallas_fold_matches_scan_on_hard_semantics():
    """Deep-lag obliterate arrival kills, overlap removers, annotate
    races, lagged fuzz logs, warm obliterate base — the riskiest step
    logic — through the Pallas port."""
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        pathlib.Path(__file__).parent.parent / "__graft_entry__.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _parity(mod._hard_mergetree_docs())


def test_padded_block_dims_satisfy_mosaic_rule():
    """The round-5 recorded Mosaic failure was a block whose dims violate
    the (8, 128) divisibility rule (``block shape (1, 96)`` vs array
    ``(1024, 96)``).  Every BlockSpec the kernel builds is (DOC_BLOCK,
    lanes) with lanes from _padded_dims — pin the invariant directly."""
    from fluidframework_tpu.ops.pallas_fold import (
        DOC_BLOCK,
        LANE,
        _padded_dims,
    )

    assert DOC_BLOCK % 8 == 0 and LANE % 128 == 0
    for D, S, T in [(1, 1, 1), (24, 96, 48), (11, 48, 24),
                    (1024, 96, 96), (8, 128, 128), (1000, 192, 130)]:
        Dp, Sp, Tp = _padded_dims(D, S, T)
        assert Dp % DOC_BLOCK == 0 and Dp >= D
        assert Sp % LANE == 0 and Sp >= S, (S, Sp)
        assert Tp % LANE == 0 and Tp >= T, (T, Tp)


def test_pallas_fold_parity_on_nondivisible_buckets():
    """Interpret-mode parity on exactly the shapes the recorded error
    names: lane dims (S, T) that are NOT multiples of 128 and a doc
    count that is not a multiple of 8 — the pad lanes/rows must be
    masked to inertness."""
    docs = [bench.synth_doc(i, 24) for i in range(11)]
    # The natural buckets must genuinely violate the rule on EVERY
    # padded axis (or the test would prove nothing): D not a multiple
    # of 8, S and T not multiples of 128.
    state, ops, _meta = pack_mergetree_batch(docs)
    D, S = state.tstart.shape
    T = ops.kind.shape[1]
    assert D % 8 != 0, f"D={D} accidentally 8-aligned"
    assert S % 128 != 0, f"S={S} accidentally 128-aligned"
    assert T % 128 != 0, f"T={T} accidentally 128-aligned"
    _parity(docs)


@pytest.mark.parametrize("seed", range(3))
def test_pallas_fold_matches_scan_on_fuzz_logs(seed):
    from fluidframework_tpu.ops.mergetree_kernel import MergeTreeDocInput
    from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz
    from fluidframework_tpu.testing.mocks import channel_log

    docs = []
    for i, spec_ in enumerate((StringFuzzSpec(annotate=True),
                               StringFuzzSpec(obliterate=True))):
        _r, factory = run_fuzz(spec_, seed=1300 + 10 * seed + i,
                               n_clients=3, rounds=8, sync_every=2)
        docs.append(MergeTreeDocInput(
            doc_id=f"fz{i}", ops=channel_log(factory, "fuzz"),
            final_seq=factory.sequencer.seq,
            final_msn=factory.sequencer.min_seq,
        ))
    _parity(docs)
