"""Wire-codec round-trip coverage for every protocol message dataclass.

Exhaustiveness is asserted dynamically: every dataclass defined in
``protocol/messages.py`` OR ``protocol/wire.py`` (the columnar batch
forms live next to the codecs) must be registered in ``MESSAGE_CODECS``
and must have a sample instance in ``SAMPLES`` below — so adding a
message type fails this suite (and fluidlint's FL-WIRE-COMPLETE rule)
until a codec and a round-trip sample exist for it.
"""

import dataclasses
import json

import numpy as np
import pytest

from fluidframework_tpu.protocol import messages as messages_mod
from fluidframework_tpu.protocol import wire as wire_mod
from fluidframework_tpu.protocol.messages import (MessageType, RawOperation,
                                                  SequencedMessage)
from fluidframework_tpu.protocol.wire import (MESSAGE_CODECS, ColumnBatch,
                                              column_batch_from_bytes,
                                              column_batch_to_bytes)


def _message_dataclasses():
    return {
        name: obj
        for mod in (messages_mod, wire_mod)
        for name, obj in vars(mod).items()
        if isinstance(obj, type) and dataclasses.is_dataclass(obj)
        and obj.__module__ == mod.__name__
    }


def _column_batch(n_docs=2):
    return ColumnBatch(
        doc_index=np.array([0] * 2 + [n_docs - 1], np.int32),
        client_index=np.array([0, 1, 2], np.int32),
        client_seq=np.array([4, 1, 9], np.int64),
        ref_seq=np.array([3, 0, 7], np.int64),
        kind=np.array([0, 1, 2], np.int8),
        key_index=np.array([31, 0, 0], np.int16),
        value=np.array([999, -3, 0], np.int64),
        char_index=np.array([0, 0, 25], np.int16),
        doc_ids=tuple(f"sw-{d:04d}" for d in range(n_docs)),
        client_ids=("sw0-d0000-c0", "sw0-d0000-c1", "sw0-d0001-c0"),
        v=1,
    )


#: at least one representative instance per message type; edge values
#: (None client_id, None contents, nested contents) ride along.
SAMPLES = {
    "RawOperation": [
        RawOperation(client_id="c1", client_seq=3, ref_seq=7,
                     type=MessageType.OP,
                     contents={"ds": "d", "channel": "text",
                               "op": {"pos": 0, "text": "hi"}}),
        RawOperation(client_id="c2", client_seq=0, ref_seq=0,
                     type=MessageType.NO_OP, contents=None),
    ],
    "SequencedMessage": [
        SequencedMessage(seq=12, client_id="c1", client_seq=3, ref_seq=7,
                         min_seq=5, type=MessageType.OP,
                         contents={"k": [1, 2, {"v": None}]},
                         timestamp=1234.5),
        SequencedMessage(seq=1, client_id=None, client_seq=-1, ref_seq=0,
                         min_seq=0, type=MessageType.JOIN, contents=None),
    ],
    "ColumnBatch": [
        _column_batch(),
        _column_batch(n_docs=1),
    ],
}


def test_codec_registry_is_exhaustive():
    classes = _message_dataclasses()
    assert classes, "no message dataclasses found"
    missing_codec = sorted(set(classes) - set(MESSAGE_CODECS))
    assert not missing_codec, (
        f"message dataclasses with no MESSAGE_CODECS entry: {missing_codec}")
    missing_sample = sorted(set(classes) - set(SAMPLES))
    assert not missing_sample, (
        f"message dataclasses with no round-trip sample: {missing_sample}")
    stale = sorted(set(MESSAGE_CODECS) - set(classes))
    assert not stale, f"MESSAGE_CODECS entries with no dataclass: {stale}"


@pytest.mark.parametrize("cls_name", sorted(SAMPLES))
def test_roundtrip(cls_name):
    encode, decode = MESSAGE_CODECS[cls_name]
    for sample in SAMPLES[cls_name]:
        wire = encode(sample)
        # the codec output must be JSON-serializable verbatim (it goes
        # straight into frame_bytes) and survive a JSON round-trip
        back = decode(json.loads(json.dumps(wire)))
        assert back == sample
        # decode . encode is the identity on the wire form too
        assert encode(back) == wire


@pytest.mark.parametrize("cls_name", sorted(SAMPLES))
def test_decode_tolerates_missing_optional_fields(cls_name):
    """Old peers omit fields added later; decoders must default them."""
    encode, decode = MESSAGE_CODECS[cls_name]
    wire = encode(SAMPLES[cls_name][0])
    required = {"RawOperation": {"clientId", "type"},
                "SequencedMessage": {"sequenceNumber", "type"},
                "ColumnBatch": {"packed"}}[cls_name]
    stripped = {k: v for k, v in wire.items() if k in required}
    back = decode(stripped)
    assert type(back).__name__ == cls_name
    if "type" in wire:
        assert encode(back)["type"] == wire["type"]


# -- columnar batch framing ---------------------------------------------------


def test_column_batch_binary_framing_roundtrip():
    batch = _column_batch()
    data = column_batch_to_bytes(batch)
    back = column_batch_from_bytes(data)
    assert back == batch
    # decode . encode is the identity on the packed form too
    assert column_batch_to_bytes(back) == data


def test_column_batch_packing_compacts_tables():
    """The wire form carries only the referenced table entries, however
    large the producer's shared in-process tables are."""
    batch = _column_batch()
    big = dataclasses.replace(
        batch,
        client_ids=tuple(batch.client_ids) + tuple(
            f"unused-{i}" for i in range(1000)),
        doc_ids=tuple(batch.doc_ids) + ("unused-doc",) * 100,
    )
    data = column_batch_to_bytes(big)
    back = column_batch_from_bytes(data)
    assert len(back.client_ids) == 3
    assert len(back.doc_ids) == 2
    # row identity survives the remap
    for i in range(len(batch)):
        assert back.materialize(i) == batch.materialize(i)


def test_column_batch_materialize_matches_boxed_envelope():
    """materialize(i) reconstructs the EXACT groupedBatch RawOperation
    the boxed generator ships — the materialization-equivalence pin."""
    batch = _column_batch()
    op = batch.materialize(0)
    assert op.contents == {
        "type": "groupedBatch", "v": 1,
        "ops": [{"clientSeq": 4, "refSeq": 3, "ds": "ds", "channel": "kv",
                 "contents": {"kind": "set", "key": "k31", "value": 999}}],
    }
    assert batch.materialize(1).contents["ops"][0]["contents"] == \
        {"kind": "increment", "delta": -3}
    assert batch.materialize(2).contents["ops"][0]["contents"] == \
        {"kind": "insert", "pos": 0, "text": "z"}


@pytest.mark.parametrize("mutate, err", [
    (lambda d: d[:8], "too short"),
    (lambda d: b"XXXX" + d[4:], "magic"),
    (lambda d: d[:len(d) - 4], "truncated"),
])
def test_column_batch_rejects_malformed_frames(mutate, err):
    data = column_batch_to_bytes(_column_batch())
    with pytest.raises(ValueError, match=err):
        column_batch_from_bytes(mutate(data))


def test_column_batch_rejects_vocabulary_violations():
    batch = _column_batch()
    bad = dataclasses.replace(
        batch, kind=np.array([0, 1, 9], np.int8))
    with pytest.raises(ValueError, match="vocabulary"):
        column_batch_from_bytes(column_batch_to_bytes(bad))
    bad = dataclasses.replace(
        batch, char_index=np.array([0, 0, 99], np.int16))
    with pytest.raises(ValueError, match="char index"):
        column_batch_from_bytes(column_batch_to_bytes(bad))
    bad = dataclasses.replace(
        batch, key_index=np.array([-7, 0, 0], np.int16))
    with pytest.raises(ValueError, match="key index"):
        column_batch_from_bytes(column_batch_to_bytes(bad))
