"""Wire-codec round-trip coverage for every protocol message dataclass.

Exhaustiveness is asserted dynamically: every dataclass defined in
``protocol/messages.py`` must be registered in ``MESSAGE_CODECS`` and
must have a sample instance in ``SAMPLES`` below — so adding a message
type fails this suite (and fluidlint's FL-WIRE-COMPLETE rule) until a
codec and a round-trip sample exist for it.
"""

import dataclasses
import json

import pytest

from fluidframework_tpu.protocol import messages as messages_mod
from fluidframework_tpu.protocol.messages import (MessageType, RawOperation,
                                                  SequencedMessage)
from fluidframework_tpu.protocol.wire import MESSAGE_CODECS


def _message_dataclasses():
    return {
        name: obj for name, obj in vars(messages_mod).items()
        if isinstance(obj, type) and dataclasses.is_dataclass(obj)
        and obj.__module__ == messages_mod.__name__
    }


#: at least one representative instance per message type; edge values
#: (None client_id, None contents, nested contents) ride along.
SAMPLES = {
    "RawOperation": [
        RawOperation(client_id="c1", client_seq=3, ref_seq=7,
                     type=MessageType.OP,
                     contents={"ds": "d", "channel": "text",
                               "op": {"pos": 0, "text": "hi"}}),
        RawOperation(client_id="c2", client_seq=0, ref_seq=0,
                     type=MessageType.NO_OP, contents=None),
    ],
    "SequencedMessage": [
        SequencedMessage(seq=12, client_id="c1", client_seq=3, ref_seq=7,
                         min_seq=5, type=MessageType.OP,
                         contents={"k": [1, 2, {"v": None}]},
                         timestamp=1234.5),
        SequencedMessage(seq=1, client_id=None, client_seq=-1, ref_seq=0,
                         min_seq=0, type=MessageType.JOIN, contents=None),
    ],
}


def test_codec_registry_is_exhaustive():
    classes = _message_dataclasses()
    assert classes, "no message dataclasses found"
    missing_codec = sorted(set(classes) - set(MESSAGE_CODECS))
    assert not missing_codec, (
        f"message dataclasses with no MESSAGE_CODECS entry: {missing_codec}")
    missing_sample = sorted(set(classes) - set(SAMPLES))
    assert not missing_sample, (
        f"message dataclasses with no round-trip sample: {missing_sample}")
    stale = sorted(set(MESSAGE_CODECS) - set(classes))
    assert not stale, f"MESSAGE_CODECS entries with no dataclass: {stale}"


@pytest.mark.parametrize("cls_name", sorted(SAMPLES))
def test_roundtrip(cls_name):
    encode, decode = MESSAGE_CODECS[cls_name]
    for sample in SAMPLES[cls_name]:
        wire = encode(sample)
        # the codec output must be JSON-serializable verbatim (it goes
        # straight into frame_bytes) and survive a JSON round-trip
        back = decode(json.loads(json.dumps(wire)))
        assert back == sample
        # decode . encode is the identity on the wire form too
        assert encode(back) == wire


@pytest.mark.parametrize("cls_name", sorted(SAMPLES))
def test_decode_tolerates_missing_optional_fields(cls_name):
    """Old peers omit fields added later; decoders must default them."""
    encode, decode = MESSAGE_CODECS[cls_name]
    wire = encode(SAMPLES[cls_name][0])
    required = {"RawOperation": {"clientId", "type"},
                "SequencedMessage": {"sequenceNumber", "type"}}[cls_name]
    stripped = {k: v for k, v in wire.items() if k in required}
    back = decode(stripped)
    assert type(back).__name__ == cls_name
    assert encode(back)["type"] == wire["type"]
