"""Perf regression gates (VERDICT r2: nothing failed when e2e regressed 40×).

Two tiers:
- HOST-STAGE budgets, runnable on any backend: pack and extract are pure
  host work whose per-op cost is hardware-stable; a generous (≈8×) margin
  over the measured cost catches order-of-magnitude regressions (a stray
  Python inner loop, a lost C++ fast path) without flaking on slow CI.
- DEVICE e2e gate vs the CPU oracle, TPU-only (on the CPU backend the
  "device" path is an XLA-emulated scan and the ratio is meaningless).
"""

import time

import jax
import numpy as np
import pytest

import bench
from fluidframework_tpu.ops.mergetree_kernel import (
    pack_mergetree_batch,
    replay_export,
    summaries_from_export,
)

N_DOCS = 256
OPS = 96

# Budgets in microseconds per op, ≈8× the cost measured on the round-3
# dev host (pack 0.6µs/op, extract 1.0µs/op for a 1024-doc chunk).
PACK_BUDGET_US = 6.0
EXTRACT_BUDGET_US = 10.0


@pytest.fixture(scope="module")
def packed_chunk():
    docs = [bench.synth_doc(i, OPS) for i in range(N_DOCS)]
    state, ops, meta = pack_mergetree_batch(docs)
    return docs, state, ops, meta


def test_pack_stage_within_budget(packed_chunk):
    docs, *_ = packed_chunk
    best = float("inf")
    for _ in range(3):  # best-of-3: absorb transient host contention
        t0 = time.time()
        pack_mergetree_batch(docs)
        best = min(best, time.time() - t0)
    per_op_us = best / (N_DOCS * OPS) * 1e6
    assert per_op_us < PACK_BUDGET_US, (
        f"pack regressed: {per_op_us:.2f}µs/op > budget {PACK_BUDGET_US}"
    )


@pytest.fixture(scope="module")
def chunk_export(packed_chunk):
    """The chunk's fetched export buffer — shared by every gate that
    reads it (one fold dispatch + download per module, not per test)."""
    from fluidframework_tpu.ops.mergetree_kernel import export_to_numpy

    _docs, state, ops, meta = packed_chunk
    return export_to_numpy(
        replay_export(None, ops, meta, S=state.tstart.shape[1])
    )


def test_extract_stage_within_budget(packed_chunk, chunk_export):
    _docs, _state, _ops, meta = packed_chunk
    export = chunk_export
    summaries_from_export(meta, export)  # warm (library load etc.)
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        summaries = summaries_from_export(meta, export)
        best = min(best, time.time() - t0)
    per_op_us = best / (N_DOCS * OPS) * 1e6
    assert len(summaries) == N_DOCS
    assert per_op_us < EXTRACT_BUDGET_US, (
        f"extract regressed: {per_op_us:.2f}µs/op > "
        f"budget {EXTRACT_BUDGET_US}"
    )


# The trend gate is RELATIVE (VERDICT r4 weak #3: an absolute ops/s pin is
# a single-machine artifact — spuriously failing on slower CI or too loose
# to catch anything): the fold rate is compared against a same-run NumPy
# calibration workload shaped like the fold's per-op state traffic (a
# cumsum + masked select over an [N_DOCS, S] int32 plane per op).  Both
# sides scale with the host's memory bandwidth and Python/BLAS dispatch
# overhead, so the RATIO is portable where the absolute rate is not.
# Committed ratio on the round-5 dev host: see
# CPU_FOLD_TO_CALIBRATION_RATIO below; the gate allows 3x slack and exists
# to catch kernel-SHAPE regressions (a lost fusion, an accidental O(S^2)
# blowup) without needing TPU.
# Round-5 dev host measurement: fold 61,201 ops/s, calibration 1,106,641
# ops/s (the same host's round-4 absolute pin was 57,400 — consistent).
CPU_FOLD_TO_CALIBRATION_RATIO = 0.055
CPU_FOLD_SLACK = 3.0
# Test hooks: multiply the measured times so the gate's failure path is
# itself testable (see test_fold_trend_gate_trips_on_slowdown).
_FOLD_TIME_INFLATION = 1.0
_CALIBRATION_TIME_INFLATION = 1.0


def _calibration_rate() -> float:
    """ops/s of a FIXED NumPy workload mirroring the fold's per-op cost
    shape: one pass of prefix-sum + masked select over the [N_DOCS, S]
    state plane per applied op.  Pure NumPy (no jax) so it tracks host
    memory bandwidth, not XLA codegen."""
    S = 192
    plane = np.arange(N_DOCS * S, dtype=np.int32).reshape(N_DOCS, S)
    best = float("inf")
    for _ in range(3):
        a = plane.copy()
        t0 = time.time()
        for _ in range(OPS):
            b = np.cumsum(a, axis=1, dtype=np.int32)
            a = np.where(b & 1, a + 1, a)
        best = min(best, time.time() - t0)
    return N_DOCS * OPS / (best * _CALIBRATION_TIME_INFLATION)


def _measured_fold_rate(packed_chunk) -> float:
    _docs, state, ops, meta = packed_chunk
    S = state.tstart.shape[1]
    ops_dev = jax.device_put(ops)
    jax.block_until_ready(ops_dev)
    jax.block_until_ready(replay_export(None, ops_dev, meta, S=S))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        jax.block_until_ready(replay_export(None, ops_dev, meta, S=S))
        best = min(best, time.time() - t0)
    return N_DOCS * OPS / (best * _FOLD_TIME_INFLATION)


@pytest.mark.skipif(
    jax.default_backend() != "cpu",
    reason="trend reference is a CPU-backend ratio",
)
def test_fold_rate_trend_gate(packed_chunk):
    rate = _measured_fold_rate(packed_chunk)
    calibration = _calibration_rate()
    ratio = rate / calibration
    floor = CPU_FOLD_TO_CALIBRATION_RATIO / CPU_FOLD_SLACK
    assert ratio > floor, (
        f"CPU-backend steady fold regressed: {rate:,.0f} ops/s is "
        f"{ratio:.3f}x the same-host calibration workload "
        f"({calibration:,.0f} ops/s) < floor {floor:.3f} "
        f"(committed ratio {CPU_FOLD_TO_CALIBRATION_RATIO})"
    )


@pytest.mark.skipif(
    jax.default_backend() != "cpu", reason="companion to the trend gate"
)
def test_fold_trend_gate_trips_on_slowdown(packed_chunk, monkeypatch):
    """The gate must actually fail under a 5x fold slowdown — otherwise it
    is decorative."""
    import sys

    # Pin the committed ratio to THIS host's measured ratio so the
    # companion trips deterministically regardless of host speed, then
    # inflate the fold side 5x.
    mod = sys.modules[__name__]
    ratio_now = _measured_fold_rate(packed_chunk) / _calibration_rate()
    monkeypatch.setattr(mod, "CPU_FOLD_TO_CALIBRATION_RATIO", ratio_now)
    monkeypatch.setattr(mod, "_FOLD_TIME_INFLATION", 5.0)
    with pytest.raises(AssertionError, match="steady fold regressed"):
        test_fold_rate_trend_gate(packed_chunk)


@pytest.mark.skipif(
    jax.default_backend() != "cpu", reason="companion to the trend gate"
)
def test_fold_trend_gate_passes_on_slower_host(packed_chunk, monkeypatch):
    """A uniformly slower host (both fold AND calibration 4x slower) must
    NOT trip the gate — that is the portability the relative measure buys
    (VERDICT r4 item 8)."""
    import sys

    mod = sys.modules[__name__]
    ratio_now = _measured_fold_rate(packed_chunk) / _calibration_rate()
    monkeypatch.setattr(mod, "CPU_FOLD_TO_CALIBRATION_RATIO", ratio_now)
    monkeypatch.setattr(mod, "_FOLD_TIME_INFLATION", 4.0)
    monkeypatch.setattr(mod, "_CALIBRATION_TIME_INFLATION", 4.0)
    test_fold_rate_trend_gate(packed_chunk)


def test_bench_emits_skip_json_when_backend_unavailable(tmp_path):
    """bench.py must never crash on a dead backend: it emits ONE parseable
    JSON line with a skipped marker and exits 0 (VERDICT r3 item 2).  The
    failure is simulated by forcing a nonexistent platform through the real
    probe path (FF_BENCH_PLATFORM applies via jax.config.update in the
    probe subprocess, beating the axon sitecustomize env force)."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(
        FF_BENCH_PLATFORM="no_such_platform",
        BENCH_PROBE_TIMEOUT="120",
        BENCH_DOCS="8", BENCH_OPS="4",
    )
    proc = subprocess.run(
        [sys.executable, "bench.py"], capture_output=True, text=True,
        timeout=300, env=env, cwd=os.path.dirname(bench.__file__),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    parsed = json.loads(lines[0])
    assert parsed["metric"] == bench.METRIC_NAME
    assert parsed["skipped"] == "backend-unavailable"
    assert "error_tail" in parsed["probe"]
    # Schema-stable cache field: present on every artifact, null when the
    # run never reached the catch-up cache phase.
    assert "cache_hit_rate" in parsed and parsed["cache_hit_rate"] is None


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="device-vs-oracle ratio only meaningful on real accelerator",
)
def test_device_e2e_beats_oracle():
    """On real TPU the pipelined e2e must beat the CPU oracle by a wide
    margin; 5× is a deliberately loose floor (the round-3 target is ≥10×)
    so the gate flags collapses, not noise."""
    docs = [bench.synth_doc(i, OPS) for i in range(2048)]
    t0 = time.time()
    for doc in docs[:16]:
        bench.oracle_replay(doc)
    cpu_rate = 16 * OPS / (time.time() - t0)
    # warm compile
    state, ops, meta = pack_mergetree_batch(docs[:1024])
    jax.block_until_ready(
        replay_export(None, ops, meta, S=state.tstart.shape[1])
    )
    summaries, _stats, _stage, wall, _packed = bench.run_e2e(docs)
    assert len(summaries) == len(docs)
    dev_rate = len(docs) * OPS / wall
    assert dev_rate > 5 * cpu_rate, (
        f"device e2e {dev_rate:,.0f} ops/s < 5x oracle {cpu_rate:,.0f}"
    )


def test_native_widen_beats_numpy_widen(packed_chunk, chunk_export):
    """Relative gate (portable across hosts): the C++ narrow→canonical
    widen must stay meaningfully faster than the numpy inverse it
    replaced on the extraction hot path.  Measured warm best-of-5 with a
    10% margin (advisor, round 5): the strict ``native < py`` form at
    millisecond scale tripped on scheduler noise, and a gate that can
    only fail on noise measures nothing — the real win is ~10×, so
    demanding ≥10% still flags a genuine regression."""
    from fluidframework_tpu.ops.mergetree_kernel import (
        _export_flags,
        widen_export,
        widen_export_native,
    )
    from fluidframework_tpu.ops.native_pack import load_library

    if load_library() is None:
        pytest.skip("liboppack unavailable")
    _docs, _state, _ops, meta = packed_chunk
    assert meta["i16_ok"], "gate needs a narrow-eligible chunk"
    ex = chunk_export
    _i16, ob_f, ov_f, i8_f, props_f = _export_flags(meta)
    args = (meta.get("doc_base"), ob_f, ov_f, i8_f, meta.get("props_K"),
            props_f)
    native = py = float("inf")
    for _ in range(2):  # warm both sides (allocator, library load)
        widen_export_native(ex, *args)
        widen_export(ex, args[0], ob_rows=ob_f, ov_rows=ov_f, i8=i8_f,
                     n_props=meta.get("props_K"), props_rows=props_f)
    for _ in range(5):
        t0 = time.time()
        assert widen_export_native(ex, *args) is not None
        native = min(native, time.time() - t0)
        t0 = time.time()
        widen_export(ex, args[0], ob_rows=ob_f, ov_rows=ov_f, i8=i8_f,
                     n_props=meta.get("props_K"), props_rows=props_f)
        py = min(py, time.time() - t0)
    assert native < py * 0.9, (
        f"native widen ({native*1e3:.2f}ms) not ≥10% faster than numpy "
        f"({py*1e3:.2f}ms)"
    )


def test_catchup_warm_hit_skips_pack_stage_entirely():
    """Warm-vs-cold catch-up gate: a full tier-1 hit must do ZERO pack
    work — asserted via the pipeline stage counters, not wall-clock, so
    the gate cannot flake on scheduler noise.  mesh=None pins the
    single-device pipelined path (the conftest's virtual 8-device mesh
    would otherwise route around the stage-instrumented pipeline)."""
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService

    n_docs, ops = 24, 16
    service = LocalOrderingService()
    doc_ids = bench.build_catchup_corpus(service, n_docs, ops)
    svc = CatchupService(service, mesh=None)

    cold = svc.catch_up(doc_ids, upload=False)
    assert svc.pipeline_stage.get("pack", 0) > 0, (
        "cold catch-up never reached the pack stage — gate miswired"
    )
    stage_after_cold = dict(svc.pipeline_stage)
    counters = svc.cache.counters

    hits_before = counters.get("hits")
    warm = svc.catch_up(doc_ids, upload=False)
    assert warm == cold, "warm catch-up changed bytes"
    assert svc.pipeline_stage == stage_after_cold, (
        f"warm hit touched pipeline stages: {svc.pipeline_stage} "
        f"vs {stage_after_cold}"
    )
    assert counters.get("hits") - hits_before == n_docs, (
        "warm pass was not a full tier-1 hit"
    )


def test_tree_catchup_warm_hit_skips_pack_stage_entirely():
    """The SECOND kernel family's warm-vs-cold gate (ISSUE 14): a warm
    tree catch-up through the real CatchupService must be a pure tier-1
    serve — every doc a cache hit (rate 1.0), the pack-stage counter and
    both byte counters untouched, bytes identical to the cold fold.
    Mirrors test_catchup_warm_hit_skips_pack_stage_entirely; mesh=None
    pins the single-device pipelined tree path."""
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService
    from tools.bench_kernels import build_tree_catchup_corpus

    n_docs, edits = 16, 24
    service = LocalOrderingService()
    doc_ids = build_tree_catchup_corpus(service, n_docs, edits)
    svc = CatchupService(service, mesh=None)

    cold = svc.catch_up(doc_ids, upload=False)
    assert svc.pipeline_stage.get("pack", 0) > 0, (
        "cold tree catch-up never reached the pack stage — gate miswired"
    )
    stage_after_cold = dict(svc.pipeline_stage)
    counters = svc.cache.counters

    hits_before = counters.get("hits")
    warm = svc.catch_up(doc_ids, upload=False)
    assert warm == cold, "warm tree catch-up changed bytes"
    assert svc.pipeline_stage == stage_after_cold, (
        f"warm tree hit touched pipeline stages: {svc.pipeline_stage} "
        f"vs {stage_after_cold}"
    )
    hit_rate = (counters.get("hits") - hits_before) / n_docs
    assert hit_rate == 1.0, (
        f"warm tree pass was not a full tier-1 hit (rate {hit_rate})"
    )


def test_narrow_upload_shrinks_op_stream(packed_chunk, monkeypatch):
    """The narrow transfer encoding must keep cutting ≥40% off the
    qualifying op-stream upload (the h2d leg of the link budget)."""
    import numpy as np

    from fluidframework_tpu.ops.mergetree_kernel import narrow_ops_for_upload

    # The documented disable switch would make this gate fail spuriously.
    monkeypatch.delenv("FF_UPLOAD_NARROW", raising=False)
    _docs, _state, ops, meta = packed_chunk
    assert meta["i16_ok"]
    wide = sum(np.asarray(x).nbytes for x in ops)
    narrow = sum(
        np.asarray(x).nbytes for x in narrow_ops_for_upload(ops, meta)
    )
    assert narrow <= wide * 0.6, (
        f"narrow upload only {wide - narrow} of {wide} bytes saved"
    )


def test_streamfold_gate_collapses_cold_folds(tmp_path):
    """The streaming-fold gate (ISSUE 16) end to end at test scale: the
    same catch-up storm with the sequencer-attached streaming fold ON
    must serve its herd joins from the streaming head / warm tiers
    (≥95%), collapse the cold folds the OFF run pays, bound the summary
    lag by the fold cadence, and leave the oplog file strictly smaller
    after summary-anchored truncation — all byte-identical to the OFF
    run.  Runs the real ``tools.loadgen --stream`` entrypoint so the
    JSON artifact contract is covered too."""
    import json

    from tools import loadgen

    out = tmp_path / "stream.json"
    rc = loadgen.main([
        "--stream", "--clients", "96", "--docs", "4", "--shards", "2",
        "--seed", "3", "--out", str(out),
    ])
    report = json.loads(out.read_text())
    stream = report["stream"]
    assert rc == 0 and stream["passed"], stream
    assert stream["converged_identical"], (
        "streaming on vs off diverged — the fold must be byte-identical"
    )
    assert stream["stream_serve_rate"] >= stream["gate_serve_rate"]
    assert stream["cold_folds_on"] < stream["cold_folds_off"], (
        f"streaming did not collapse cold folds: "
        f"{stream['cold_folds_on']} vs {stream['cold_folds_off']}"
    )
    assert stream["stream_summary_lag_max_seqs"] \
        <= stream["stream_lag_gate_seqs"]
    assert stream["truncated_msgs"] > 0
    assert 0 < stream["oplog_bytes_on"] \
        < stream["oplog_bytes_untruncated_on"], (
        "summary-anchored truncation did not shrink the durable log"
    )
    assert stream["oplog_bytes_reclaimed"] > 0
