"""Perf regression gates (VERDICT r2: nothing failed when e2e regressed 40×).

Two tiers:
- HOST-STAGE budgets, runnable on any backend: pack and extract are pure
  host work whose per-op cost is hardware-stable; a generous (≈8×) margin
  over the measured cost catches order-of-magnitude regressions (a stray
  Python inner loop, a lost C++ fast path) without flaking on slow CI.
- DEVICE e2e gate vs the CPU oracle, TPU-only (on the CPU backend the
  "device" path is an XLA-emulated scan and the ratio is meaningless).
"""

import time

import jax
import numpy as np
import pytest

import bench
from fluidframework_tpu.ops.mergetree_kernel import (
    pack_mergetree_batch,
    replay_export,
    summaries_from_export,
)

N_DOCS = 256
OPS = 96

# Budgets in microseconds per op, ≈8× the cost measured on the round-3
# dev host (pack 0.6µs/op, extract 1.0µs/op for a 1024-doc chunk).
PACK_BUDGET_US = 6.0
EXTRACT_BUDGET_US = 10.0


@pytest.fixture(scope="module")
def packed_chunk():
    docs = [bench.synth_doc(i, OPS) for i in range(N_DOCS)]
    state, ops, meta = pack_mergetree_batch(docs)
    return docs, state, ops, meta


def test_pack_stage_within_budget(packed_chunk):
    docs, *_ = packed_chunk
    best = float("inf")
    for _ in range(3):  # best-of-3: absorb transient host contention
        t0 = time.time()
        pack_mergetree_batch(docs)
        best = min(best, time.time() - t0)
    per_op_us = best / (N_DOCS * OPS) * 1e6
    assert per_op_us < PACK_BUDGET_US, (
        f"pack regressed: {per_op_us:.2f}µs/op > budget {PACK_BUDGET_US}"
    )


def test_extract_stage_within_budget(packed_chunk):
    _docs, state, ops, meta = packed_chunk
    export = np.asarray(
        replay_export(None, ops, meta, S=state.tstart.shape[1])
    )
    summaries_from_export(meta, export)  # warm (library load etc.)
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        summaries = summaries_from_export(meta, export)
        best = min(best, time.time() - t0)
    per_op_us = best / (N_DOCS * OPS) * 1e6
    assert len(summaries) == N_DOCS
    assert per_op_us < EXTRACT_BUDGET_US, (
        f"extract regressed: {per_op_us:.2f}µs/op > "
        f"budget {EXTRACT_BUDGET_US}"
    )


@pytest.mark.skipif(
    jax.default_backend() == "cpu",
    reason="device-vs-oracle ratio only meaningful on real accelerator",
)
def test_device_e2e_beats_oracle():
    """On real TPU the pipelined e2e must beat the CPU oracle by a wide
    margin; 5× is a deliberately loose floor (the round-3 target is ≥10×)
    so the gate flags collapses, not noise."""
    docs = [bench.synth_doc(i, OPS) for i in range(2048)]
    t0 = time.time()
    for doc in docs[:16]:
        bench.oracle_replay(doc)
    cpu_rate = 16 * OPS / (time.time() - t0)
    # warm compile
    state, ops, meta = pack_mergetree_batch(docs[:1024])
    jax.block_until_ready(
        replay_export(None, ops, meta, S=state.tstart.shape[1])
    )
    summaries, _stats, _stage, wall, _packed = bench.run_e2e(docs)
    assert len(summaries) == len(docs)
    dev_rate = len(docs) * OPS / wall
    assert dev_rate > 5 * cpu_rate, (
        f"device e2e {dev_rate:,.0f} ops/s < 5x oracle {cpu_rate:,.0f}"
    )
