"""SharedMap / SharedDirectory: LWW convergence + optimistic local reads."""

from fluidframework_tpu.dds import SharedMap, SharedDirectory
from fluidframework_tpu.testing import MockContainerRuntimeFactory


def make_pair(cls=SharedMap):
    factory = MockContainerRuntimeFactory()
    a = factory.create_client("A").attach(cls("m"))
    b = factory.create_client("B").attach(cls("m"))
    return factory, a, b


def test_set_converges():
    factory, a, b = make_pair()
    a.set("k", 1)
    factory.process_all_messages()
    assert a.get("k") == b.get("k") == 1


def test_concurrent_set_last_sequenced_wins():
    factory, a, b = make_pair()
    a.set("k", "fromA")
    b.set("k", "fromB")  # submitted second → sequenced second → wins
    factory.process_all_messages()
    assert a.get("k") == b.get("k") == "fromB"


def test_pending_local_outranks_incoming_remote():
    factory, a, b = make_pair()
    a.set("k", "old")
    factory.process_all_messages()
    b.set("k", "fromB")
    factory.process_all_messages()  # B's op sequenced
    # A sets while B's value is already sequenced-in: A's op sequences later.
    a.set("k", "fromA")
    assert a.get("k") == "fromA"  # optimistic local read
    factory.process_all_messages()
    assert a.get("k") == b.get("k") == "fromA"


def test_interleaved_delivery_preserves_optimistic_read():
    factory, a, b = make_pair()
    b.set("k", "fromB")
    a.set("k", "fromA")
    # Deliver only B's op: A must keep its pending value (it sequences later).
    factory.process_some_messages(1)
    assert a.get("k") == "fromA"
    assert b.get("k") == "fromB"
    factory.process_all_messages()
    assert a.get("k") == b.get("k") == "fromA"


def test_delete_and_clear_converge():
    factory, a, b = make_pair()
    a.set("x", 1)
    a.set("y", 2)
    factory.process_all_messages()
    b.delete("x")
    a.clear()
    factory.process_all_messages()
    assert len(a) == len(b) == 0


def test_pending_set_survives_remote_clear():
    factory, a, b = make_pair()
    a.set("x", 1)
    factory.process_all_messages()
    b.clear()
    a.set("y", 2)  # concurrent with the clear, sequenced after it
    factory.process_all_messages()
    assert not a.has("x") and not b.has("x")
    assert a.get("y") == b.get("y") == 2


def test_map_summary_roundtrip_byte_identical():
    factory, a, b = make_pair()
    a.set("k1", [1, 2, {"z": 3}])
    b.set("k2", "v")
    a.delete("missing")
    factory.process_all_messages()
    sa, sb = a.summarize(), b.summarize()
    assert sa.digest() == sb.digest()  # replicas byte-identical
    fresh = SharedMap("m")
    fresh.load(sa)
    assert fresh.get("k1") == [1, 2, {"z": 3}]
    assert fresh.summarize().digest() == sa.digest()


def test_directory_clear_survives_subdir_reset():
    """Regression: an in-flight clear whose kernel is deleted/recreated
    underneath it must still apply on its ack (and not underflow the pending
    counter)."""
    factory, a, b = make_pair(SharedDirectory)
    a.create_subdirectory("a")
    a.set("k", 1, path="a")
    factory.process_all_messages()
    b.delete_subdirectory("a")
    b.set("k", 9, path="a")  # recreates the subdir, sequenced before A's clear
    a.clear(path="a")        # in-flight while the reset lands
    factory.process_all_messages()
    assert a.summarize().digest() == b.summarize().digest()
    assert a.get("k", path="a") is None and b.get("k", path="a") is None
    # Counter must not have underflowed: a later remote set applies normally.
    b.set("k2", 5, path="a")
    factory.process_all_messages()
    assert a.get("k2", path="a") == 5


def test_directory_subdirs_and_convergence():
    factory, a, b = make_pair(SharedDirectory)
    a.create_subdirectory("sub/inner")
    a.set("k", 1, path="sub/inner")
    b.set("top", True)
    factory.process_all_messages()
    assert b.get("k", path="sub/inner") == 1
    assert a.get("top") == b.get("top") is True
    assert a.summarize().digest() == b.summarize().digest()
    b.delete_subdirectory("sub/inner")
    factory.process_all_messages()
    assert a.get("k", path="sub/inner") is None
    assert a.summarize().digest() == b.summarize().digest()
