"""Service slice: durable op log, scribe ack/nack, checkpoints/crash-resume,
multi-document ordering service, bulk catch-up (CPU + device paths)."""

import pytest

from fluidframework_tpu.protocol.messages import (
    MessageType,
    RawOperation,
)
from fluidframework_tpu.protocol.sequencer import Sequencer
from fluidframework_tpu.protocol.summary import SummaryStorage
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.runtime.summarizer import (
    SummarizerOptions,
    SummaryManager,
)
from fluidframework_tpu.service import (
    LocalOrderingService,
    OpLog,
)
from fluidframework_tpu.service.catchup import CatchupService


def op(client, client_seq, ref_seq=0, contents=None):
    return RawOperation(
        client_id=client, client_seq=client_seq, ref_seq=ref_seq,
        type=MessageType.OP, contents=contents or {"k": client_seq},
    )


# --- OpLog -------------------------------------------------------------------


def test_oplog_ranged_reads():
    service = LocalOrderingService()
    ep = service.create_document("d1")
    ep.connect("a")
    for i in range(1, 6):
        ep.submit(op("a", i))
    # seq 1 is the JOIN; ops are seqs 2..6
    assert service.oplog.head("d1") == 6
    tail = ep.deltas(from_seq=3)
    assert [m.seq for m in tail] == [4, 5, 6]
    window = ep.deltas(from_seq=1, to_seq=4)
    assert [m.seq for m in window] == [2, 3, 4]


def test_oplog_file_persistence(tmp_path):
    path = str(tmp_path / "ops.jsonl")
    log = OpLog(path)
    service = LocalOrderingService(oplog=log)
    ep = service.create_document("doc")
    ep.connect("a")
    for i in range(1, 4):
        ep.submit(op("a", i, contents={"text": f"op{i}"}))
    log.close()

    reopened = OpLog(path)
    assert reopened.head("doc") == 4
    msgs = reopened.get("doc")
    assert [m.seq for m in msgs] == [1, 2, 3, 4]
    assert msgs[0].type is MessageType.JOIN
    assert msgs[1].contents == {"text": "op1"}


# --- Scribe ------------------------------------------------------------------


def _connected_runtime_with_string(service, doc_id, client_id):
    ep = service.create_document(doc_id) if not service.has_document(doc_id) \
        else service.endpoint(doc_id)
    runtime = ContainerRuntime()
    ds = runtime.create_datastore("ds")
    text = ds.create_channel("sequence-tpu", "text")
    runtime.connect(ep, client_id)
    runtime.drain()
    return runtime, ds, text, ep


def test_scribe_acks_valid_summary():
    service = LocalOrderingService()
    runtime, _ds, text, ep = _connected_runtime_with_string(
        service, "doc", "a"
    )
    mgr = SummaryManager(runtime, service.storage, "doc",
                         SummarizerOptions(ops_per_summary=1000))
    text.insert_text(0, "hello")
    runtime.drain()
    mgr.summarize_now()
    runtime.drain()
    orderer = service._orderers["doc"]
    assert orderer.scribe.acks == 1
    assert orderer.scribe.nacks == 0
    assert mgr.last_acked_handle == orderer.scribe.last_acked_handle
    # ack is a durable, sequenced message
    types = [m.type for m in ep.log]
    assert MessageType.SUMMARY_ACK in types


def test_scribe_nacks_unknown_handle():
    service = LocalOrderingService()
    ep = service.create_document("doc")
    ep.connect("a")
    ep.submit(
        RawOperation(
            client_id="a", client_seq=1, ref_seq=0,
            type=MessageType.SUMMARIZE,
            contents={"handle": "deadbeef", "seq": 0},
        )
    )
    orderer = service._orderers["doc"]
    assert orderer.scribe.nacks == 1
    nacks = [m for m in ep.log if m.type is MessageType.SUMMARY_NACK]
    assert len(nacks) == 1
    assert "unknown" in nacks[0].contents["reason"]


def test_scribe_nacks_stale_summary():
    service = LocalOrderingService()
    runtime, _ds, text, ep = _connected_runtime_with_string(
        service, "doc", "a"
    )
    mgr = SummaryManager(runtime, service.storage, "doc",
                         SummarizerOptions(ops_per_summary=1000))
    text.insert_text(0, "hello")
    runtime.drain()
    first = mgr.summarize_now()
    runtime.drain()
    # Re-announce an older summary point than the accepted one.
    stale_seq = runtime.ref_seq
    text.insert_text(5, " world")
    runtime.drain()
    second = mgr.summarize_now()
    runtime.drain()
    assert second != first
    # Now replay the *first* (older ref_seq) announcement again.
    orderer = service._orderers["doc"]
    before_nacks = orderer.scribe.nacks
    ep.submit(
        RawOperation(
            client_id="a", client_seq=999, ref_seq=runtime.ref_seq,
            type=MessageType.SUMMARIZE,
            contents={"handle": first, "seq": 1},
        )
    )
    assert orderer.scribe.nacks == before_nacks + 1


# --- checkpoints / crash-resume ----------------------------------------------


def test_sequencer_checkpoint_roundtrip():
    seq = Sequencer()
    seq.connect("a")
    seq.connect("b")
    seq.submit(op("a", 1, ref_seq=1))
    seq.submit(op("b", 1, ref_seq=2))
    state = seq.checkpoint()
    restored = Sequencer.restore(state)
    assert restored.seq == seq.seq
    assert restored.min_seq == seq.min_seq
    # dedup floors survive: an old client_seq is still rejected
    assert restored.submit(op("a", 1, ref_seq=2)) is None
    assert restored.submit(op("a", 2, ref_seq=2)) is not None


def test_crash_resume_from_stale_checkpoint(tmp_path):
    """Checkpoint taken early; more ops land; service crashes.  The restored
    orderer must resume from the durable log exactly-once: no re-stamping,
    dedup floors reconstructed from the tail."""
    path = str(tmp_path / "ops.jsonl")
    log = OpLog(path)
    service = LocalOrderingService(oplog=log)
    ep = service.create_document("doc")
    ep.connect("a")
    ep.submit(op("a", 1))
    checkpoint = service.checkpoint()  # taken at seq 2
    ep.submit(op("a", 2))
    ep.submit(op("a", 3, ref_seq=3))
    log.close()  # "crash"

    log2 = OpLog(path)
    restored = LocalOrderingService.restore(
        log2, SummaryStorage(), checkpoint
    )
    ep2 = restored.endpoint("doc")
    assert ep2.head_seq == 4  # JOIN + 3 ops, none re-stamped
    # dedup floor covers ops sequenced after the checkpoint
    assert ep2.submit(op("a", 3, ref_seq=3)) is None
    msg = ep2.submit(op("a", 4, ref_seq=4))
    assert msg is not None and msg.seq == 5
    assert log2.head("doc") == 5


def test_endpoint_recovers_doc_from_log_only(tmp_path):
    """Service restarted with no checkpoint at all: a document that exists
    only in the durable log is recovered by full log replay."""
    path = str(tmp_path / "ops.jsonl")
    log = OpLog(path)
    service = LocalOrderingService(oplog=log)
    ep = service.create_document("doc")
    ep.connect("a")
    ep.submit(op("a", 1))
    ep.submit(op("a", 2))
    log.close()

    service2 = LocalOrderingService(oplog=OpLog(path))
    assert service2.has_document("doc")
    ep2 = service2.endpoint("doc")
    assert ep2.head_seq == 3
    assert ep2.submit(op("a", 2)) is None  # dedup floor recovered
    assert ep2.submit(op("a", 3)) is not None


def test_reconnect_same_client_after_crash_resume(tmp_path):
    """A surviving client reconnects with its old id + session after the
    service restores: the record resumes (no duplicate JOIN), the dedup
    floor survives, and disconnecting the truly-dead client unpins the
    MSN."""
    path = str(tmp_path / "ops.jsonl")
    service = LocalOrderingService(oplog=OpLog(path))
    ep = service.create_document("doc")
    ep.connect("alive", session="sess-alive")
    ep.connect("dead", session="sess-dead")
    ep.submit(op("alive", 1, ref_seq=2))
    ep.submit(op("dead", 1, ref_seq=2))
    checkpoint = service.checkpoint()
    service.oplog.close()

    restored = LocalOrderingService.restore(
        OpLog(path), SummaryStorage(), checkpoint
    )
    ep2 = restored.endpoint("doc")
    joins_before = sum(1 for m in ep2.log if m.type is MessageType.JOIN)
    ep2.connect("alive", session="sess-alive")  # resume: no duplicate JOIN
    assert sum(1 for m in ep2.log if m.type is MessageType.JOIN) \
        == joins_before
    assert ep2.submit(op("alive", 1, ref_seq=2)) is None  # floor survived
    # the dead client pins the MSN until the host disconnects it
    ep2.disconnect("dead")
    msg = ep2.submit(op("alive", 2, ref_seq=ep2.head_seq))
    assert msg.min_seq == msg.ref_seq


def test_fresh_session_reusing_client_id_gets_fresh_floor():
    """A NEW session (different/no session token) reusing a client id must
    not inherit the old dedup floor — its restarted client_seqs would be
    silently swallowed."""
    service = LocalOrderingService()
    ep = service.create_document("doc")
    ep.connect("bob", session="one")
    ep.submit(op("bob", 1))
    ep.submit(op("bob", 2))
    # fresh session, same id — submits against a CURRENT view (a stale
    # ref below the collaboration window would be op-nacked)
    ep.connect("bob", session="two")
    msg = ep.submit(op("bob", 1, ref_seq=ep.head_seq))  # client_seq restarts
    assert msg is not None
    # the swap is visible in the stream as LEAVE + JOIN
    types = [m.type for m in ep.log]
    assert types.count(MessageType.JOIN) == 2
    assert types.count(MessageType.LEAVE) == 1


def test_signals_are_unsequenced():
    service = LocalOrderingService()
    ep = service.create_document("doc")
    ep.connect("a")
    seen = []
    ep.subscribe_signals(seen.append)
    head_before = ep.head_seq
    ep.submit_signal("a", {"cursor": 7})
    ep.submit_signal("a", {"cursor": 8}, target_client_id="b")
    assert [s["content"]["cursor"] for s in seen] == [7, 8]
    assert seen[1]["targetClientId"] == "b"
    assert ep.head_seq == head_before  # nothing sequenced, nothing logged


# --- bulk catch-up -----------------------------------------------------------


def _seed_string_doc(service, doc_id, edits, n_clients=2):
    """Attach a single-string-channel doc (initial summary at seq 0), then
    drive `edits` ops through connected runtimes."""
    ep = service.create_document(doc_id)
    seeded = ContainerRuntime()
    ds = seeded.create_datastore("ds")
    ds.create_channel("sequence-tpu", "text")
    service.storage.upload(doc_id, seeded.summarize(), 0)

    runtimes = []
    for c in range(n_clients):
        rt = ContainerRuntime()
        rt.load(service.storage.latest(doc_id)[0])
        rt.connect(ep, f"client{c}")
        rt.drain()
        runtimes.append(rt)

    import random
    rng = random.Random(doc_id)
    for i in range(edits):
        rt = runtimes[i % n_clients]
        text = rt.get_datastore("ds").get_channel("text")
        length = len(text.text)
        if length < 4 or rng.random() < 0.7:
            text.insert_text(rng.randint(0, length), "ab"[i % 2] * 3)
        else:
            start = rng.randint(0, length - 2)
            text.remove_range(start, min(length, start + 2))
        for r in runtimes:
            r.drain()
    return runtimes


def test_catchup_cpu_vs_device_byte_identical():
    service = LocalOrderingService()
    for d in range(3):
        _seed_string_doc(service, f"doc{d}", edits=12)

    cpu = CatchupService(service)
    # force CPU by making the device plan fail
    cpu._device_plan = lambda w: None
    cpu_results = cpu.catch_up(upload=False)

    dev = CatchupService(service)
    dev_results = dev.catch_up(upload=False)
    assert dev.device_docs == 3
    assert cpu_results == dev_results


def test_catchup_uploads_and_is_incremental():
    service = LocalOrderingService()
    runtimes = _seed_string_doc(service, "doc", edits=8)
    svc = CatchupService(service)
    first = svc.catch_up()
    handle, seq = first["doc"]
    latest_tree, latest_seq = service.storage.latest("doc")
    assert latest_tree.digest() == handle and latest_seq == seq

    # no new ops: same handle, no re-upload of a new commit
    again = svc.catch_up()
    assert again["doc"] == (handle, seq)

    # a loading client starts from the fresh summary with an empty tail
    loader_rt = ContainerRuntime()
    loaded_seq = loader_rt.load(latest_tree)
    tail = service.oplog.get("doc", from_seq=loaded_seq)
    assert tail == []
    live_text = runtimes[0].get_datastore("ds").get_channel("text")
    assert (
        loader_rt.get_datastore("ds").get_channel("text").text
        == live_text.text
    )


def test_catchup_preserves_seeded_attach_content():
    """A doc whose attach summary carries seeded (detached-created) content
    warm-folds on the device: the summary body re-enters the kernel as
    base_records and the seed survives byte-for-byte."""
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader

    service = LocalOrderingService()
    loader = Loader(LocalDocumentServiceFactory(service))

    def build(rt):
        ds = rt.create_datastore("ds")
        text = ds.create_channel("sequence-tpu", "text")
        text.insert_text(0, "SEEDED-")

    a = loader.create("doc", "alice", build)
    a.runtime.get_datastore("ds").get_channel("text").insert_text(7, "tail")
    a.drain()

    svc = CatchupService(service)
    svc.catch_up()
    assert svc.device_docs == 1 and svc.cpu_docs == 0

    fresh = loader.resolve("doc")
    text = fresh.runtime.get_datastore("ds").get_channel("text").text
    assert text == "SEEDED-tail"


def test_catchup_warm_start_from_prior_summary_on_device():
    """THE north-star shape: catch-up = prior summary + op tail, folded on
    device repeatedly, byte-identical to the CPU fold every round."""
    service = LocalOrderingService()
    runtimes = _seed_string_doc(service, "doc", edits=10)
    svc = CatchupService(service)
    first = svc.catch_up()
    assert svc.device_docs == 1  # cold round

    import random
    rng = random.Random("warm")
    for round_idx in range(3):
        for i in range(8):
            rt = runtimes[i % len(runtimes)]
            t = rt.get_datastore("ds").get_channel("text")
            L = len(t.text)
            if L < 4 or rng.random() < 0.7:
                t.insert_text(rng.randint(0, L), f"w{round_idx}")
            else:
                s = rng.randint(0, L - 2)
                t.remove_range(s, min(L, s + 2))
            for r in runtimes:
                r.drain()
        before_dev = svc.device_docs
        # device fold vs a forced-CPU fold of the same (summary, tail)
        cpu = CatchupService(service)
        cpu._device_plan = lambda w: None
        cpu_result = cpu.catch_up(upload=False)
        result = svc.catch_up()
        assert svc.device_docs == before_dev + 1  # warm round on device
        handle, seq = result["doc"]
        assert cpu_result["doc"] == (handle, seq)

    # the final summary loads clean with an empty tail
    tree, seq = service.storage.latest("doc")
    assert service.oplog.get("doc", from_seq=seq) == []
    check = ContainerRuntime()
    check.load(tree)
    live = runtimes[0].get_datastore("ds").get_channel("text").text
    assert check.get_datastore("ds").get_channel("text").text == live


def test_catchup_mixed_eligibility():
    """String AND map docs both ride the device plan (map kernels route
    through catch-up since round 3); results land for both."""
    service = LocalOrderingService()
    _seed_string_doc(service, "strdoc", edits=6)

    ep = service.create_document("mapdoc")
    seeded = ContainerRuntime()
    ds = seeded.create_datastore("ds")
    ds.create_channel("map-tpu", "kv")
    service.storage.upload("mapdoc", seeded.summarize(), 0)
    rt = ContainerRuntime()
    rt.load(service.storage.latest("mapdoc")[0])
    rt.connect(ep, "m0")
    rt.drain()
    kv = rt.get_datastore("ds").get_channel("kv")
    kv.set("x", 1)
    kv.set("y", 2)
    rt.drain()

    svc = CatchupService(service)
    results = svc.catch_up()
    assert svc.device_docs == 2 and svc.cpu_docs == 0
    assert set(results) == {"strdoc", "mapdoc"}

    tree, _seq = service.storage.latest("mapdoc")
    check = ContainerRuntime()
    check.load(tree)
    loaded_kv = check.get_datastore("ds").get_channel("kv")
    assert loaded_kv.get("x") == 1 and loaded_kv.get("y") == 2


def _drive_mixed_doc(runtimes, rng, rounds=6):
    """Random traffic across all channels of the mixed-type datastore."""
    for i in range(rounds):
        rt = runtimes[i % len(runtimes)]
        ds = rt.get_datastore("ds")
        roll = rng.random()
        if roll < 0.3:
            t = ds.get_channel("text")
            L = len(t.text)
            if L < 4 or rng.random() < 0.7:
                t.insert_text(rng.randint(0, L), "xy"[i % 2] * 2)
            else:
                s = rng.randint(0, L - 2)
                t.remove_range(s, min(L, s + 2))
        elif roll < 0.5:
            ds.get_channel("kv").set(f"k{rng.randint(0, 5)}",
                                     rng.randint(0, 99))
        elif roll < 0.7:
            m = ds.get_channel("grid")
            if m.row_count == 0 or rng.random() < 0.4:
                m.insert_rows(rng.randint(0, m.row_count), 1)
            elif m.col_count == 0 or rng.random() < 0.6:
                m.insert_cols(rng.randint(0, m.col_count), 1)
            else:
                m.set_cell(rng.randint(0, m.row_count - 1),
                           rng.randint(0, m.col_count - 1),
                           rng.randint(0, 99))
        elif roll < 0.9:
            tr = ds.get_channel("tree")
            from fluidframework_tpu.dds.tree import ROOT_ID
            kids = tr.children(ROOT_ID, "a")
            if not kids or rng.random() < 0.7:
                tr.insert(ROOT_ID, "a", rng.randint(0, len(kids)),
                          [tr.build("n", value=rng.randint(0, 9))])
            else:
                tr.set_value(rng.choice(kids), rng.randint(0, 99))
        else:
            ds.get_channel("clicks").increment(1)
        for r in runtimes:
            r.drain()


def test_catchup_mixed_types_fold_on_device():
    """A mixed population (string+map+matrix+tree+counter channels, warm
    rounds included) routes through the device plan: kernel channels fold
    on device, the counter folds host-side per channel, and every summary
    is byte-identical to the forced-CPU container fold."""
    service = LocalOrderingService()
    rng = __import__("random").Random("mixed")
    all_runtimes = {}
    for d in range(3):
        doc_id = f"mixed{d}"
        ep = service.create_document(doc_id)
        seeded = ContainerRuntime()
        ds = seeded.create_datastore("ds")
        ds.create_channel("sequence-tpu", "text")
        ds.create_channel("map-tpu", "kv")
        ds.create_channel("matrix-tpu", "grid")
        ds.create_channel("tree-tpu", "tree")
        ds.create_channel("counter-tpu", "clicks")
        service.storage.upload(doc_id, seeded.summarize(), 0)
        runtimes = []
        for c in range(2):
            rt = ContainerRuntime()
            rt.load(service.storage.latest(doc_id)[0])
            rt.connect(ep, f"client{c}")
            rt.drain()
            runtimes.append(rt)
        all_runtimes[doc_id] = runtimes
        _drive_mixed_doc(runtimes, rng, rounds=8)

    svc = CatchupService(service)
    cpu = CatchupService(service)
    cpu._device_plan = lambda w: None

    for round_idx in range(2):  # cold round, then a warm round
        cpu_results = cpu.catch_up(upload=False)
        results = svc.catch_up()
        assert svc.device_docs == 3 * (round_idx + 1), (
            "mixed docs must ride the device plan"
        )
        assert svc.cpu_docs == 0
        assert svc.host_channels > 0  # the counter folded host-side
        for doc_id, (handle, seq) in results.items():
            assert cpu_results[doc_id] == (handle, seq), (
                f"{doc_id}: device summary != CPU container fold"
            )
        for runtimes in all_runtimes.values():
            _drive_mixed_doc(runtimes, rng, rounds=6)


def test_catchup_host_fold_observes_leave():
    """A consensus queue in a device-routed doc must see the tail's LEAVE
    (a departed client's held items re-queue via observe_protocol) — the
    host-side channel fold replays protocol messages, not just channel
    ops, byte-identical to the CPU container fold."""
    service = LocalOrderingService()
    ep = service.create_document("qdoc")
    seeded = ContainerRuntime()
    ds = seeded.create_datastore("ds")
    ds.create_channel("ordered-collection-tpu", "queue")
    ds.create_channel("sequence-tpu", "text")
    service.storage.upload("qdoc", seeded.summarize(), 0)

    worker = ContainerRuntime()
    worker.load(service.storage.latest("qdoc")[0])
    worker.connect(ep, "worker")
    worker.drain()
    other = ContainerRuntime()
    other.load(service.storage.latest("qdoc")[0])
    other.connect(ep, "observer")
    other.drain()

    q = worker.get_datastore("ds").get_channel("queue")
    q.add("job-1")
    worker.drain()
    other.drain()
    q.acquire()
    worker.drain()
    other.drain()
    assert q.held_by_me
    # the worker dies holding the item: LEAVE lands in the tail and the
    # held item re-queues (nothing stays held by the departed client)
    ep.disconnect("worker")
    other.drain()
    other_q = other.get_datastore("ds").get_channel("queue")
    assert other_q.items == ["job-1"] and other_q.holder_of("item-0") is None

    svc = CatchupService(service)
    cpu = CatchupService(service)
    cpu._device_plan = lambda w: None
    cpu_results = cpu.catch_up(upload=False)
    results = svc.catch_up(upload=False)
    assert svc.device_docs == 1 and svc.host_channels >= 1
    assert results["qdoc"] == cpu_results["qdoc"], (
        "host channel fold diverged from the container fold on LEAVE"
    )
