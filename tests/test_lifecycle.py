"""Resource-lifecycle regressions (ISSUE 5, fluidleak): idempotent
close/shutdown across the serving stack — the in-repo negative fixtures
for FL-LEAK-DOUBLE-CLOSE — plus the "leader died without reaching its
finally" single-flight scenario the exit-path rules exist to prevent.

Each close here is reachable from more than one call path in production
(`_ClientSession.close` from the laggard-drop AND the connection unwind,
`_RpcClient.close` from the factory AND error-path callers, the file
factory from host teardown AND atexit sweeps, `Container.close` from
hosts AND `close_and_get_pending_state`); a second call must be a no-op,
never a re-run of the release protocol.
"""

import socket
import threading
import time

import pytest

import bench
from fluidframework_tpu.drivers import FileDocumentServiceFactory
from fluidframework_tpu.drivers.network_driver import _RpcClient
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.service import LocalOrderingService
from fluidframework_tpu.service.catchup import CatchupService
from fluidframework_tpu.service.server import OrderingServer, _ClientSession

from tests.test_loader import build_text_doc, make_stack


# --- _ClientSession.close (service/server.py) --------------------------------


def test_session_close_idempotent():
    """The laggard-drop path closes mid-connection and _handle's finally
    closes again on unwind: the second close must not re-run the
    unsubscribe/disconnect sweep (it would tear down listeners a
    reconnected session re-registered in between)."""
    service, _factory, loader = make_stack()
    loader.create("doc", "alice", build_text_doc).drain()
    server = OrderingServer(service)
    session = _ClientSession(server, writer=None)
    session.tap("doc")
    session.connected_clients["c1"] = "doc"

    endpoint_calls = []
    real_endpoint = service.endpoint

    def counting_endpoint(doc_id):
        endpoint_calls.append(doc_id)
        return real_endpoint(doc_id)

    service.endpoint = counting_endpoint
    assert server.broadcaster.subscriber_count("doc") == 1
    session.close()
    assert endpoint_calls, "first close must run the release sweep"
    assert not session.subscribed_docs and not session.connected_clients
    assert server.broadcaster.subscriber_count("doc") == 0

    # A "reconnected session" re-registers between the two closes (the
    # double-close hazard this pins): the broadcaster tap of the NEW
    # session must survive the old session's second close.
    session2 = _ClientSession(server, writer=None)
    session2.tap("doc")
    endpoint_calls.clear()
    session.close()
    assert endpoint_calls == [], "second close must be a no-op"
    assert server.broadcaster.subscriber_count("doc") == 1


# --- _RpcClient.close (drivers/network_driver.py) ----------------------------


class _CountingSocket:
    """Delegating socket proxy that counts release calls."""

    def __init__(self, sock):
        self._sock = sock
        self.shutdowns = 0
        self.closes = 0

    def shutdown(self, how):
        self.shutdowns += 1
        return self._sock.shutdown(how)

    def close(self):
        self.closes += 1
        return self._sock.close()

    def __getattr__(self, name):
        return getattr(self._sock, name)


def test_rpc_client_close_idempotent():
    """close() is reachable from the factory, error-path callers, and
    teardown sweeps; only the FIRST call may touch the socket.  The
    `_closed` request-gate flag alone cannot be the guard — a dead
    reader sets it without ever closing the fd."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    host, port = listener.getsockname()
    try:
        client = _RpcClient(host, port)
        server_side, _addr = listener.accept()
        counted = _CountingSocket(client._sock)
        client._sock = counted

        client.close()
        assert (counted.shutdowns, counted.closes) == (1, 1)
        client.close()
        client.close()
        assert (counted.shutdowns, counted.closes) == (1, 1), (
            "second close re-ran the socket release")
        # shutdown(SHUT_RDWR) delivered EOF: both driver threads exit
        # (the daemon-leak contract of test_concurrency.py).
        client._reader.join(timeout=10)
        client._dispatcher.join(timeout=10)
        assert not client._reader.is_alive()
        assert not client._dispatcher.is_alive()
        server_side.close()
    finally:
        listener.close()


def test_rpc_dispatcher_surfaces_subscriber_errors():
    """The FL-LEAK-SWALLOW fix: a broken subscriber must not kill event
    delivery (the old contract) but its failure must reach the telemetry
    logger instead of vanishing in a bare `except: pass` (the new one)."""
    from fluidframework_tpu.utils.telemetry import (CollectingLogger,
                                                    MonitoringContext)

    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    host, port = listener.getsockname()
    sink = CollectingLogger()
    try:
        client = _RpcClient(host, port, mc=MonitoringContext(logger=sink))
        server_side, _addr = listener.accept()
        delivered = threading.Event()
        client.on("op", "doc", lambda frame: (_ for _ in ()).throw(
            ValueError("broken subscriber")))
        client.on("op", "doc", lambda frame: delivered.set())
        # Feed the dispatcher directly: routing is the dispatcher's own
        # job; the wire framing is test_network.py's concern.
        client._events.put({"event": "op", "doc": "doc"})
        assert delivered.wait(timeout=10), (
            "a broken subscriber killed delivery to the next one")
        errors = [e for e in sink.events
                  if e.get("eventName", "").endswith("subscriberError")]
        assert errors and errors[0]["errorType"] == "ValueError"
        assert client.last_sink_error is None
        # ... and a sink that ITSELF raises must not kill the dispatcher:
        # the failure lands in last_sink_error, and delivery continues.
        sink.send = lambda event: (_ for _ in ()).throw(
            OSError("sink disk full"))
        redelivered = threading.Event()
        client.on("op", "doc2", lambda frame: (_ for _ in ()).throw(
            ValueError("still broken")))
        client.on("op", "doc2", lambda frame: redelivered.set())
        client._events.put({"event": "op", "doc": "doc2"})
        assert redelivered.wait(timeout=10), (
            "a broken telemetry sink killed the dispatcher")
        assert isinstance(client.last_sink_error, OSError)
        client.close()
        server_side.close()
    finally:
        listener.close()


# --- FileDocumentServiceFactory.close (drivers/file_driver.py) ---------------


def test_file_factory_close_idempotent(tmp_path):
    """A factory closed from both a host teardown and a with-block/atexit
    sweep must flush+close the op log exactly once; the second close must
    not flush (fsync on a closed fd raises) or reopen anything."""
    factory = FileDocumentServiceFactory(str(tmp_path / "store"))
    loader = Loader(factory)
    container = loader.create("doc", "alice", build_text_doc)
    container.drain()

    oplog = factory.service.oplog
    flushes = []
    real_flush = oplog.flush

    def counting_flush():
        flushes.append(1)
        return real_flush()

    oplog.flush = counting_flush
    factory.close()
    assert len(flushes) == 1 and oplog._file is None
    factory.close()
    assert len(flushes) == 1, "second close must not re-flush a closed fd"


# --- Container.close (loader/loader.py) --------------------------------------


def test_container_close_idempotent():
    """close() is called directly by hosts AND by
    close_and_get_pending_state(); the disconnect protocol (LEAVE
    submission, listener teardown) must run once."""
    _service, _factory, loader = make_stack()
    container = loader.create("doc", "alice", build_text_doc)
    container.drain()

    dm_closes = []
    real_close = container.delta_manager.close

    def counting_close():
        dm_closes.append(1)
        return real_close()

    container.delta_manager.close = counting_close
    state = container.close_and_get_pending_state()
    assert container.closed and dm_closes == [1]
    container.close()  # the host's own teardown arrives second
    assert dm_closes == [1], "double close re-ran the disconnect protocol"
    assert state["docId"] == "doc"


def test_container_close_failure_stays_retryable():
    """The idempotency flag must be set AFTER the disconnect protocol
    succeeds: a dead connection raising mid-close must not latch
    closed=True and turn every retry into a no-op with the live-delta
    subscription still registered."""
    _service, _factory, loader = make_stack()
    container = loader.create("doc", "alice", build_text_doc)
    container.drain()

    real_close = container.delta_manager.close
    calls = []

    def flaky_close():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("connection dead")
        return real_close()

    container.delta_manager.close = flaky_close
    with pytest.raises(RuntimeError):
        container.close()
    assert not container.closed, "failed close latched the flag"
    container.close()  # retry must actually run the protocol
    assert container.closed and calls == [1, 1]
    container.close()  # and a third call is the idempotent no-op
    assert calls == [1, 1]


# --- single-flight: leader killed mid-fold (service/catchup.py) --------------


def test_crashed_leader_mid_fold_abandons_flight_and_wakes_waiters():
    """The exact scenario catchup.py's finally-abandon exists for: the
    fold raises out from under the single-flight leader.  The herd
    waiting on that flight must wake well within join_timeout (via the
    abandon, NOT the timeout), no flight object may survive in the
    cache, and the followers must re-fold to the byte-identical result —
    while the leader's own exception propagates (never swallowed)."""
    service = LocalOrderingService()
    bench.build_catchup_corpus(service, 1, 12)
    svc = CatchupService(service, mesh=None)
    svc.join_timeout = 60.0  # generous: abandon must win, not the timer

    folding = threading.Event()
    release = threading.Event()
    fold_calls = []
    real_fold = svc._device_fold

    def dying_fold(works):
        fold_calls.append(len(works))
        if len(fold_calls) == 1:
            folding.set()
            assert release.wait(timeout=30)
            raise RuntimeError("leader killed mid-fold")
        return real_fold(works)

    svc._device_fold = dying_fold
    results = {}
    errors = {}

    def run(name):
        try:
            results[name] = svc.catch_up(["cdoc0"], upload=False)
        except RuntimeError as exc:
            errors[name] = str(exc)

    leader = threading.Thread(target=run, args=("leader",))
    leader.start()
    assert folding.wait(timeout=30)  # the key is now in flight
    waiters = [threading.Thread(target=run, args=(f"w{i}",))
               for i in range(4)]
    for t in waiters:
        t.start()
    time.sleep(0.05)  # let the herd reach join() on the live flight
    t0 = time.monotonic()
    release.set()  # the fold raises: leader dies, finally abandons
    leader.join(timeout=60)
    for t in waiters:
        t.join(timeout=60)
    elapsed = time.monotonic() - t0

    assert errors == {"leader": "leader killed mid-fold"}, (
        "the injected failure must propagate from the leader, unswallowed")
    assert elapsed < svc.join_timeout / 2, (
        "waiters woke via the timeout, not the finally-abandon")
    assert svc.cache._flights == {}, "a flight object survived the crash"
    assert set(results) == {f"w{i}" for i in range(4)}
    # One waiter re-led and re-folded; the rest served from its publish.
    assert fold_calls == [1, 1], fold_calls
    fresh = CatchupService(service, cache=None, mesh=None)
    expected = fresh.catch_up(["cdoc0"], upload=False)
    assert all(r == expected for r in results.values())
