"""Incremental summaries: unchanged subtrees upload as handle references.

The reference's incremental-summary capability (SURVEY.md §3.3): a summary
whose document barely changed since the last one must not re-upload the
unchanged subtrees — they ride as handles to the previous summary.  The
rebuilt tree must stay byte-identical to a full summarize.
"""

import json

from fluidframework_tpu.protocol.summary import (
    SummaryStorage,
    canonical_json,
    tree_from_obj,
    tree_to_incremental_obj,
    tree_to_obj,
)
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.runtime.summarizer import (
    SummarizerOptions,
    SummaryManager,
)
from fluidframework_tpu.service import LocalOrderingService


def _connected(service, doc_id, client_id):
    if not service.has_document(doc_id):
        ep = service.create_document(doc_id)
    else:
        ep = service.endpoint(doc_id)
    rt = ContainerRuntime()
    ds = rt.create_datastore("ds")
    ds.create_channel("sequence-tpu", "text")
    ds.create_channel("map-tpu", "kv")
    rt.connect(ep, client_id)
    rt.drain()
    return rt, ep


def test_incremental_obj_collapses_unchanged_subtrees():
    service = LocalOrderingService()
    rt, ep = _connected(service, "doc", "a")
    ep.connect("idle")  # lagging client pins the MSN: normalization stable
    rt.get_datastore("ds").get_channel("text").insert_text(0, "x" * 2000)
    rt.drain()
    first = rt.summarize()
    rt.get_datastore("ds").get_channel("kv").set("tiny", 1)
    rt.drain()
    second = rt.summarize()

    full = canonical_json(tree_to_obj(second))
    incr_obj = tree_to_incremental_obj(second, first)
    incr = canonical_json(incr_obj)
    assert len(incr) < len(full) / 3, (
        f"incremental upload {len(incr)}B should be far below "
        f"full {len(full)}B"
    )
    # the unchanged 2000-char text channel collapsed to a handle
    assert b'"h":' in incr and b"xxxx" not in incr

    # rebuild through a store that has the base: byte-identical
    storage = SummaryStorage()
    storage.upload("doc", first, 1)
    handle = storage.upload_obj("doc", incr_obj, 2)
    assert handle == second.digest()
    assert storage.read(handle).digest() == second.digest()


def test_summary_manager_uploads_incrementally():
    """Driven through the live summarizer loop: after the first summary,
    later summaries of a barely-changed large doc upload a small fraction
    of the full bytes, and loads stay byte-identical."""
    service = LocalOrderingService()
    rt, ep = _connected(service, "doc", "a")
    ep.connect("idle")  # pin the MSN so unchanged channels stay byte-stable
    mgr = SummaryManager(rt, service.storage, "doc",
                         SummarizerOptions(ops_per_summary=1000))
    text = rt.get_datastore("ds").get_channel("text")
    text.insert_text(0, "payload " * 500)  # ~4KB of stable text
    rt.drain()
    mgr.summarize_now()
    rt.drain()  # observe our own summarize announcement

    rt.get_datastore("ds").get_channel("kv").set("delta", "small")
    rt.drain()
    handle = mgr.summarize_now()
    assert mgr.last_upload_bytes < mgr.last_full_bytes / 3, (
        f"{mgr.last_upload_bytes}B uploaded vs {mgr.last_full_bytes}B full"
    )
    loaded = ContainerRuntime()
    loaded.load(service.storage.read(handle))
    assert loaded.summarize().digest() == handle
    assert loaded.get_datastore("ds").get_channel("kv").get("delta") == \
        "small"


def test_incremental_upload_falls_back_without_base():
    """A handle referencing an object the store does not have raises —
    callers then send the full tree (never silently wrong)."""
    import pytest

    storage = SummaryStorage()
    with pytest.raises(KeyError):
        storage.upload_obj("doc", {"v": 1, "t": {"x": {"h": "deadbeef"}}}, 1)


def test_network_upload_shrinks_on_the_wire(tmp_path):
    """Over the TCP driver: the second upload of a barely-changed doc sends
    a much smaller summary payload than the first."""
    from fluidframework_tpu.drivers.network_driver import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.service.server import OrderingServer

    srv = OrderingServer(port=0)
    srv.start_in_thread()
    factory = NetworkDocumentServiceFactory(port=srv.port)

    seeded = ContainerRuntime()
    ds = seeded.create_datastore("ds")
    ds.create_channel("sequence-tpu", "text")
    svc = factory.create_document("doc", seeded.summarize())

    rt = ContainerRuntime()
    rt.load(svc.storage.latest()[0])
    rt.connect(svc.connection(), "alice")
    svc.connection().connect("idle")  # pin the MSN
    rt.drain()
    rt.get_datastore("ds").get_channel("text").insert_text(0, "y" * 3000)
    rt.drain()
    first_obj = tree_to_incremental_obj(rt.summarize(), None)
    first_size = len(json.dumps(first_obj))
    svc.storage.upload(rt.summarize(), rt.ref_seq)

    rt.get_datastore("ds").get_channel("text").insert_text(0, "z")
    rt.drain()
    second = rt.summarize()
    handle = svc.storage.upload(second, rt.ref_seq)
    # the driver cached the previous upload; measure what it would send
    incr_size = len(json.dumps(
        tree_to_incremental_obj(second, svc.storage._last_uploaded)
    ))
    assert incr_size < first_size
    # server rebuilt the full tree from the incremental payload
    fetched = svc.storage.read(handle)
    assert fetched.digest() == second.digest()
    factory.close()
