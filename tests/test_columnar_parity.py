"""Columnar wire-path parity (ISSUE 11): the boxed per-op path is the
byte-identical oracle for the columnar one.

The tentpole contract, fuzz-pinned here: for every scenario family and
seed, a ``columnar=True`` run and a ``columnar=False`` (boxed) run of
the same spec produce

- byte-identical per-document op logs (every stamped message, wire
  form compared),
- identical sampled-document digests and per-doc heads,
- bit-identical telemetry counters and the full replay-identity surface
  (``SwarmResult.identity()``),

including under a mid-run shard kill (failover-drill) and injected
mid-batch durable-append faults — whose deferral recovery must
round-trip through the boxed fallback without forking the log.  A
durable (file-backed) pair additionally pins the reopened per-doc
records byte-for-byte.

Plus the columnar unit surfaces underneath: vectorized dedup floors,
the partial-unwind abort contract, lazy segments in the op log, and the
live-broadcast-subscriber fallback.
"""

import dataclasses

import numpy as np
import pytest

from fluidframework_tpu.protocol.messages import (BatchAbortedError,
                                                  MessageType)
from fluidframework_tpu.protocol.sequencer import Sequencer
from fluidframework_tpu.protocol.summary import canonical_json
from fluidframework_tpu.protocol.wire import (ColumnBatch, ColumnSegment,
                                              encode_sequenced_message)
from fluidframework_tpu.service.oplog import OpLog
from fluidframework_tpu.service.orderer import LocalOrderingService
from fluidframework_tpu.service.sharding import ShardedOrderingService
from fluidframework_tpu.testing.faults import (FaultInjector, FaultPlan,
                                               FaultPoint)
from fluidframework_tpu.testing.scenarios import (SCENARIOS, ClientSwarm,
                                                  build_scenario)


def _run(spec):
    swarm = ClientSwarm(spec)
    result = swarm.run()
    return swarm, result


def _doc_wire_log(service, doc_id):
    return [encode_sequenced_message(m)
            for m in service.oplog.get(doc_id)]


def _assert_parity(spec):
    col_swarm, col = _run(spec)
    box_swarm, box = _run(dataclasses.replace(spec, columnar=False))
    # the full replay-identity surface: metrics, counters, defers,
    # fault observations, per-phase attribution
    assert col.identity() == box.identity()
    # byte-identical per-document op logs, JOINs and all
    for doc_id in col_swarm.doc_ids:
        assert _doc_wire_log(col_swarm.service, doc_id) == \
            _doc_wire_log(box_swarm.service, doc_id), doc_id
    return col, box


@pytest.mark.parametrize("seed", [1, 5, 11])
@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_columnar_off_is_byte_identical(name, seed):
    spec = build_scenario(name, seed=seed, clients=500, docs=6, shards=4)
    col, _box = _assert_parity(spec)
    assert col.joins == 500
    if name == "failover-drill":
        assert col.kills, "the scheduled mid-run shard kill must execute"


def test_parity_under_injected_midbatch_append_faults():
    """Mid-batch durable faults abort the columnar stamp partway; the
    deferral recovery round-trips through the boxed fallback and the
    logs still converge byte-identically — faults cost deferrals, never
    state, in EITHER mode."""
    spec = build_scenario("failover-drill", seed=9, clients=600, docs=6,
                          shards=4)
    plan = FaultPlan(seed=9, points=spec.plan.points + (
        FaultPoint("oplog.append", "fail", doc="sw-0002", at=5, count=2),
        FaultPoint("oplog.append", "fail", at=200, count=1),
    ))
    spec = dataclasses.replace(spec, plan=plan)
    col, box = _assert_parity(spec)
    assert col.defers or col.join_defers, \
        "the injected faults must actually defer a batch"
    assert col.fault_counts.get("oplog.append:fail", 0) >= 2
    assert col.defers == box.defers


@pytest.mark.parametrize("unfiltered_at", [800, 1200, 1600])
def test_parity_with_mixed_boxed_columnar_tick_and_global_fault(
        unfiltered_at):
    """Regression pin for the single-sorted-interleaving requirement: a
    doc-scoped fault forces one document onto the boxed pending path
    while its neighbours stay columnar, and an UNFILTERED
    occurrence-indexed fault must still fire on the same global append
    in both modes — the mixed submit runs every document in ONE sorted
    pass, never boxed-then-columnar."""
    spec = build_scenario("steady-typing", seed=8, clients=600, docs=6,
                          shards=4)
    plan = FaultPlan(seed=8, points=(
        # past sw-0003's ~100 ramp JOINs: hits an OP batch mid-run, so
        # the doc defers and resubmits BOXED next tick
        FaultPoint("oplog.append", "fail", doc="sw-0003", at=150,
                   count=2),
        FaultPoint("oplog.append", "fail", at=unfiltered_at, count=1),
    ))
    spec = dataclasses.replace(spec, plan=plan)
    col, box = _assert_parity(spec)
    assert col.defers, "the doc-scoped fault must force an op deferral"
    assert col.fault_counts == box.fault_counts


def test_submit_mixed_appends_in_one_sorted_pass(tmp_path):
    """Direct pin on the interleaving: boxed and columnar documents in
    one submit_mixed call append to the shared durable file in ONE
    sorted per-doc order — never all-boxed-then-all-columnar."""
    log = OpLog(str(tmp_path / "ops.jsonl"), autoflush=True)
    service = LocalOrderingService(oplog=log)
    for d in ("a", "b", "c", "d"):
        service.create_document(d).connect_columns([f"{d}-c"])
    batch = _batch(("b-c", "d-c"), [1, 1], doc_ids=("b", "d"),
                   doc_idx=[0, 1])
    from fluidframework_tpu.protocol.messages import (MessageType as MT,
                                                      RawOperation)

    def op(cid):
        return RawOperation(client_id=cid, client_seq=1, ref_seq=0,
                            type=MT.OP, contents={"n": 1})

    out = service.submit_mixed(
        {"a": [op("a-c")], "c": [op("c-c")]},
        batch, {"b": np.array([0]), "d": np.array([1])})
    assert all(o.error is None for o in out.values())
    log.close()
    import json as _json

    docs_in_file = [_json.loads(line)["doc"]
                    for line in open(tmp_path / "ops.jsonl")]
    # 8 JOINs (per create/connect call order), then the 4 ops sorted
    assert docs_in_file[-4:] == ["a", "b", "c", "d"]


def test_durable_file_records_are_byte_identical_per_doc(tmp_path):
    """File-backed pair: reopening both durable logs yields per-doc
    record streams whose canonical encodings match byte-for-byte (the
    cross-doc interleaving of the shared file is NOT part of the
    contract — per-document streams are)."""
    spec = build_scenario("steady-typing", seed=4, clients=400, docs=4,
                          shards=4)
    col_spec = dataclasses.replace(spec, dir=str(tmp_path / "col"))
    box_spec = dataclasses.replace(spec, columnar=False,
                                   dir=str(tmp_path / "box"))
    col_swarm, col = _run(col_spec)
    box_swarm, box = _run(box_spec)
    assert col.sampled_digests == box.sampled_digests
    col_swarm.service.oplog.close()
    box_swarm.service.oplog.close()
    reopened_col = OpLog(str(tmp_path / "col" / "swarm-ops.jsonl"))
    reopened_box = OpLog(str(tmp_path / "box" / "swarm-ops.jsonl"))
    assert reopened_col.doc_ids() == reopened_box.doc_ids()
    for doc_id in reopened_col.doc_ids():
        col_bytes = [canonical_json(encode_sequenced_message(m))
                     for m in reopened_col.get(doc_id)]
        box_bytes = [canonical_json(encode_sequenced_message(m))
                     for m in reopened_box.get(doc_id)]
        assert col_bytes == box_bytes, doc_id


# -- columnar unit surfaces ---------------------------------------------------


def _batch(client_ids, cs, refs=None, doc_ids=("doc",), doc_idx=None):
    n = len(cs)
    return ColumnBatch(
        doc_index=np.array(doc_idx or [0] * n, np.int32),
        client_index=np.arange(n, dtype=np.int32),
        client_seq=np.array(cs, np.int64),
        ref_seq=np.array(refs or [0] * n, np.int64),
        kind=np.zeros(n, np.int8),
        key_index=np.zeros(n, np.int16),
        value=np.arange(n, dtype=np.int64),
        char_index=np.zeros(n, np.int16),
        doc_ids=doc_ids,
        client_ids=client_ids,
    )


def test_submit_columns_vectorized_dedup_floor():
    """numpy compare-and-max dedup: a whole-batch resubmit stamps
    nothing; a mixed batch stamps only the fresh rows."""
    service = LocalOrderingService()
    ep = service.create_document("doc")
    ep.connect_columns(["a", "b"])
    first = _batch(("a", "b"), [1, 1])
    out = service.submit_columns(first, {"doc": np.arange(2)})
    assert out["doc"].n_stamped() == 2
    # resubmit: both rows dedup; one fresh row rides along
    mixed = _batch(("a", "b", "a"), [1, 1, 2])
    # same client twice -> the vectorized path refuses, boxed runs it:
    out = service.submit_columns(mixed, {"doc": np.arange(3)})
    assert out["doc"].n_stamped() == 1
    assert out["doc"].consumed == 3
    assert service.oplog.head("doc") == 5  # 2 JOINs + 3 OPs


def test_submit_columns_abort_unwinds_suffix_and_resubmits_clean():
    """The BatchAbortedError contract on the columnar path: landed rows
    stay durable, the un-landed suffix unwinds (seq, floors), and the
    whole-batch resubmit re-sequences at the SAME numbers."""
    plan = FaultPlan(points=(
        # occurrences 1-3 are the JOINs; the 5th append (2nd op) fails
        FaultPoint("oplog.append", "fail", at=5, count=1),))
    service = LocalOrderingService(oplog=OpLog(faults=FaultInjector(plan)))
    ep = service.create_document("doc")
    ep.connect_columns(["a", "b", "c"])
    batch = _batch(("a", "b", "c"), [1, 1, 1])
    out = service.submit_columns(batch, {"doc": np.arange(3)})
    assert out["doc"].consumed == 1
    assert out["doc"].n_stamped() == 1
    assert out["doc"].error is not None
    assert service.oplog.head("doc") == 4  # 3 JOINs + 1 landed op
    retry = service.submit_columns(batch, {"doc": np.arange(3)})
    assert retry["doc"].error is None
    assert retry["doc"].n_stamped() == 2  # dedup absorbed the prefix
    seqs = [m.seq for m in service.oplog.get("doc")]
    assert seqs == list(range(1, 7))


def test_submit_columns_with_live_subscriber_falls_back_boxed():
    """A live broadcast subscriber forces per-message materialization:
    the document takes the boxed path and the subscriber sees every
    message in order."""
    service = LocalOrderingService()
    ep = service.create_document("doc")
    seen = []
    ep.subscribe(seen.append)
    ep.connect_columns(["a"])  # falls back boxed too: JOIN is broadcast
    batch = _batch(("a",), [1])
    out = service.submit_columns(batch, {"doc": np.arange(1)})
    assert out["doc"].stamped_count is None  # boxed outcome shape
    assert [m.client_id for m in seen if m.type is MessageType.OP] == ["a"]
    # and the log holds real messages, not a lazy segment
    entries = service.oplog._docs["doc"]
    assert not any(isinstance(e, ColumnSegment) for e in entries)


def test_columnar_stamps_store_lazy_segments():
    """No live subscribers: the op log stores ONE columnar segment for
    the batch; head() is O(1) on it and get() materializes on read."""
    service = LocalOrderingService()
    ep = service.create_document("doc")
    ep.connect_columns(["a", "b"])
    out = service.submit_columns(_batch(("a", "b"), [1, 1]),
                                 {"doc": np.arange(2)})
    assert out["doc"].stamped_count == 2
    entries = service.oplog._docs["doc"]
    assert isinstance(entries[-1], ColumnSegment)
    assert len(entries[-1]) == 2
    assert service.oplog.head("doc") == 4
    assert service.oplog.is_contiguous("doc")
    msgs = service.oplog.get("doc", from_seq=3)
    assert [(m.seq, m.client_id, m.type) for m in msgs] == \
        [(4, "b", MessageType.OP)]


def test_connect_columns_matches_boxed_connect_many():
    """Bulk JOIN cohorts stamp byte-identical to N boxed connects, and
    re-joining (resume semantics) falls back to the boxed path."""
    # drive through services so the durable gate exists on both sides
    sa = LocalOrderingService()
    sa.create_document("d").connect_columns(["x", "y"], session="s1")
    sb = LocalOrderingService()
    sb.create_document("d").connect_many(["x", "y"], session="s1")
    assert [encode_sequenced_message(m) for m in sa.oplog.get("d")] == \
        [encode_sequenced_message(m) for m in sb.oplog.get("d")]
    # resume: columnar refuses known ids, boxed resume stamps nothing
    head = sa.oplog.head("d")
    sa.endpoint("d").connect_columns(["x"], session="s1")
    assert sa.oplog.head("d") == head


def test_sharded_assignment_refreshes_on_fence():
    service = ShardedOrderingService(n_shards=4)
    docs = [f"doc{i}" for i in range(8)]
    for d in docs:
        service.create_document(d)
    before = service.shard_assignment(docs)
    victim = service.shard_of("doc0")
    service.kill_shard(victim)
    after = service.shard_assignment(docs)
    order = service.router.shard_ids()
    assert order[int(before[0])] == victim
    assert order[int(after[0])] != victim  # doc0 re-owned
    # untouched docs keep their owner (rendezvous moves only the dead
    # shard's documents)
    for i, d in enumerate(docs):
        if order[int(before[i])] != victim:
            assert before[i] == after[i], d


def test_submit_columns_across_shards_after_kill_recovers():
    """Columnar ingress keeps the post-failover no-special-case
    contract: the tick after a kill, the cached assignment refreshed and
    every document lands on its recovered owner."""
    service = ShardedOrderingService(n_shards=4)
    docs = ["d0", "d1", "d2", "d3"]
    for d in docs:
        service.create_document(d).connect_columns([f"{d}-c"])
    batch = _batch(tuple(f"{d}-c" for d in docs), [1, 1, 1, 1],
                   doc_ids=tuple(docs), doc_idx=[0, 1, 2, 3])
    out = service.submit_columns(
        batch, {d: np.array([i]) for i, d in enumerate(docs)})
    assert all(o.error is None for o in out.values())
    service.kill_shard(service.shard_of("d0"))
    batch2 = _batch(tuple(f"{d}-c" for d in docs), [2, 2, 2, 2],
                    doc_ids=tuple(docs), doc_idx=[0, 1, 2, 3])
    out2 = service.submit_columns(
        batch2, {d: np.array([i]) for i, d in enumerate(docs)})
    for d, o in out2.items():
        assert o.error is None, (d, o.error)
        assert o.n_stamped() == 1
    for d in docs:
        assert service.oplog.is_contiguous(d)


def test_submit_columns_batch_abort_carries_boxed_consumed_semantics():
    """consumed counts dup rows before the failing row — exactly the
    boxed BatchAbortedError accounting."""
    seq = Sequencer()

    def gate(segment):
        from fluidframework_tpu.protocol.messages import ColumnAppendError
        raise ColumnAppendError(1, RuntimeError("refused"))

    seq.connect_many(["a", "b", "c"])
    # row 0 is a duplicate (floor already at 1), rows 1-2 fresh
    first = _batch(("a",), [1])
    seq.submit_columns(first, np.arange(1), lambda s: None)
    batch = _batch(("a", "b", "c"), [1, 1, 1])
    with pytest.raises(BatchAbortedError) as err:
        seq.submit_columns(batch, np.arange(3), gate)
    # row 0 dedup'd (consumed), row 1 landed (consumed), row 2 failed
    assert err.value.consumed == 2
    assert [m.client_id for m in err.value.stamped] == ["b"]
