"""Merge-tree / SharedString: convergence, tie-breaks, windows, summaries."""

from fluidframework_tpu.dds import SharedString
from fluidframework_tpu.testing import MockContainerRuntimeFactory


def make_clients(n=2):
    factory = MockContainerRuntimeFactory()
    strings = [
        factory.create_client(chr(ord("A") + i)).attach(SharedString("s"))
        for i in range(n)
    ]
    return factory, strings


def assert_converged(factory, strings):
    factory.process_all_messages()
    texts = {s.text for s in strings}
    assert len(texts) == 1, f"divergence: {[s.text for s in strings]}"
    digests = {s.summarize().digest() for s in strings}
    assert len(digests) == 1, "summary divergence"
    return strings[0].text


def test_basic_insert_remove():
    factory, (a, b) = make_clients()
    a.insert_text(0, "hello world")
    factory.process_all_messages()
    b.remove_range(5, 11)
    b.insert_text(5, "!")
    assert_converged(factory, [a, b])
    assert a.text == "hello!"


def test_concurrent_insert_same_position_newest_first():
    factory, (a, b) = make_clients()
    a.insert_text(0, "AAA")
    b.insert_text(0, "BBB")  # sequenced second → newer → placed first
    text = assert_converged(factory, [a, b])
    assert text == "BBBAAA"


def test_concurrent_insert_interior_position():
    factory, (a, b) = make_clients()
    a.insert_text(0, "0123456789")
    factory.process_all_messages()
    a.insert_text(5, "aa")
    b.insert_text(5, "bb")
    text = assert_converged(factory, [a, b])
    assert text == "01234bbaa56789"


def test_three_way_concurrent_inserts_stack_newest_first():
    factory, (a, b, c) = make_clients(3)
    a.insert_text(0, "A")
    b.insert_text(0, "B")
    c.insert_text(0, "C")
    text = assert_converged(factory, [a, b, c])
    assert text == "CBA"


def test_insert_into_concurrently_removed_range_survives():
    factory, (a, b) = make_clients()
    a.insert_text(0, "0123456789")
    factory.process_all_messages()
    a.remove_range(2, 8)
    b.insert_text(5, "XYZ")  # inside the range A is removing
    text = assert_converged(factory, [a, b])
    assert text == "01XYZ89"


def test_overlapping_concurrent_removes():
    factory, (a, b) = make_clients()
    a.insert_text(0, "0123456789")
    factory.process_all_messages()
    a.remove_range(0, 6)
    b.remove_range(4, 9)
    text = assert_converged(factory, [a, b])
    assert text == "9"


def test_remote_ops_interleaved_with_pending_local():
    factory, (a, b) = make_clients()
    a.insert_text(0, "base")
    factory.process_all_messages()
    # A edits locally; B's concurrent ops are delivered before A's sequence.
    a.insert_text(4, "-tail")
    b.insert_text(0, "head-")
    b.remove_range(0, 1)  # depends on B's own pending insert
    factory.process_all_messages()
    assert a.text == b.text
    assert a.text == "ead-base-tail"


def test_position_resolution_uses_op_view():
    factory, (a, b) = make_clients()
    a.insert_text(0, "abcdef")
    factory.process_all_messages()
    a.remove_range(0, 3)  # A's view: "def"
    b.insert_text(6, "!")  # B's view: "abcdef", append at end
    text = assert_converged(factory, [a, b])
    assert text == "def!"


def test_annotate_lww_and_pending_priority():
    factory, (a, b) = make_clients()
    a.insert_text(0, "styled")
    factory.process_all_messages()
    a.annotate_range(0, 6, {"bold": True})
    factory.process_all_messages()
    b.annotate_range(0, 6, {"bold": False, "size": 12})
    a.annotate_range(0, 3, {"size": 14})  # sequenced after b's → wins on [0,3)
    factory.process_all_messages()
    assert a.summarize().digest() == b.summarize().digest()
    recs = a.tree.normalized_records()
    assert recs[0]["p"] == {"bold": False, "size": 14}
    assert recs[1]["p"] == {"bold": False, "size": 12}


def test_annotate_null_deletes_property():
    factory, (a, b) = make_clients()
    a.insert_text(0, "xy")
    a.annotate_range(0, 2, {"k": 1})
    factory.process_all_messages()
    b.annotate_range(0, 2, {"k": None})
    factory.process_all_messages()
    recs = a.tree.normalized_records()
    assert "p" not in recs[0]
    assert a.summarize().digest() == b.summarize().digest()


def test_zamboni_collects_tombstones_after_window_advance():
    factory, (a, b) = make_clients()
    a.insert_text(0, "0123456789")
    factory.process_all_messages()
    a.remove_range(2, 8)
    factory.process_all_messages()
    assert any(s.removed_seq is not None for s in a.tree.segments)
    factory.advance_min_seq()
    factory.process_all_messages()
    assert all(s.removed_seq is None for s in a.tree.segments)
    assert a.text == b.text == "0189"
    assert a.summarize().digest() == b.summarize().digest()


def test_summary_roundtrip_through_fresh_client():
    factory, (a, b) = make_clients()
    a.insert_text(0, "persistent state")
    b.annotate_range(0, 10, {"mark": 1})
    b.remove_range(10, 16)
    factory.process_all_messages()
    summary = a.summarize()
    fresh = SharedString("s")
    fresh.load(summary)
    assert fresh.text == a.text
    assert fresh.summarize().digest() == summary.digest()


def test_normalization_clamps_old_seqs():
    factory, (a, b) = make_clients()
    a.insert_text(0, "one")
    factory.process_all_messages()
    b.insert_text(3, "two")
    factory.process_all_messages()
    factory.advance_min_seq()
    recs = a.tree.normalized_records()
    # Everything below MSN clamps to the universal epoch and merges.
    assert recs == [{"t": "onetwo", "s": 0, "c": None}]


def test_beast_style_random_soak_two_clients():
    """Randomized interleaved edit soak (the reference's beastTest shape)."""
    import random

    rng = random.Random(0xF1D)
    factory, strings = make_clients(3)
    alphabet = "abcdefghijklmnopqrstuvwxyz"
    for round_no in range(60):
        for s in strings:
            for _ in range(rng.randint(0, 3)):
                n = len(s)
                action = rng.random()
                if action < 0.55 or n == 0:
                    pos = rng.randint(0, n)
                    text = "".join(rng.choice(alphabet) for _ in range(rng.randint(1, 5)))
                    s.insert_text(pos, text)
                elif action < 0.8:
                    start = rng.randint(0, n - 1)
                    end = min(n, start + rng.randint(1, 6))
                    s.remove_range(start, end)
                else:
                    start = rng.randint(0, n - 1)
                    end = min(n, start + rng.randint(1, 6))
                    s.annotate_range(start, end, {"k": rng.randint(0, 3)})
        # Deliver a random prefix of the queue to explore interleavings.
        factory.process_some_messages(rng.randint(0, factory.pending_count))
        if round_no % 10 == 9:
            factory.process_all_messages()
            factory.advance_min_seq()
    assert_converged(factory, strings)


def test_obliterate_fuzz_converges_bounded_lag():
    """Obliterate under concurrency: 3 clients submit concurrent batches
    (inserts/removes/annotates/obliterates) optimistically, syncing each
    round — every replica converges to identical text and summary bytes.
    (Deep-lag partial delivery is covered by the tests below.)"""
    import random as _random

    from fluidframework_tpu.testing.fuzz import StringFuzzSpec
    from fluidframework_tpu.testing.mocks import MockContainerRuntimeFactory

    spec = StringFuzzSpec(obliterate=True)
    for seed in range(25):
        rng = _random.Random(seed)
        factory = MockContainerRuntimeFactory()
        replicas = []
        for i in range(3):
            client = factory.create_client(f"client{i}")
            replicas.append(client.attach(spec.create("fuzz")))
        for round_no in range(15):
            for replica in replicas:
                for _ in range(3):
                    if rng.random() < spec.op_probability:
                        spec.random_op(rng, replica)
            factory.process_all_messages()
            texts = {r.text for r in replicas}
            assert len(texts) == 1, f"seed {seed} round {round_no}: {texts}"
            if rng.random() < 0.5:
                factory.advance_min_seq()
        digests = {r.summarize().digest() for r in replicas}
        assert len(digests) == 1, f"seed {seed}: divergent summaries"


def test_obliterate_kills_concurrent_insert():
    """The defining behavior: an insert into a concurrently obliterated
    range dies; the same insert into a merely removed range survives."""
    from fluidframework_tpu.testing.mocks import MockContainerRuntimeFactory

    for kind, expect in (("obliterate", "AD"), ("remove", "AxD")):
        factory = MockContainerRuntimeFactory()
        a = factory.create_client("a").attach(SharedString("doc"))
        b = factory.create_client("b").attach(SharedString("doc"))
        a.insert_text(0, "ABCD")
        factory.process_all_messages()
        # concurrent: a obliterates/removes [1,3) while b inserts at 2
        getattr(a, f"{kind}_range")(1, 3)
        b.insert_text(2, "x")
        factory.process_all_messages()
        assert a.text == b.text == expect, f"{kind}: {a.text!r}"


# --- deep-lag obliterate convergence (partial delivery) ----------------------


def _run_lag_script(script, n_clients):
    """Drive a scripted interleaving with PARTIAL delivery points; assert
    all replicas converge to byte-identical summaries at the end."""
    from fluidframework_tpu.testing.mocks import MockContainerRuntimeFactory

    factory = MockContainerRuntimeFactory()
    reps = [factory.create_client(f"c{i}").attach(SharedString("d"))
            for i in range(n_clients)]
    for step in script:
        if step[0] == "sync":
            factory.process_some_messages(
                min(step[1], factory.pending_count))
            continue
        _, c, kind, a, b = step
        r = reps[c % n_clients]
        n = len(r.text)
        if kind == "ins":
            r.insert_text(min(a, n), "xyzw"[:max(1, b)])
        elif kind == "ob":
            if n > 0:
                s = min(a, n - 1)
                r.obliterate_range(s, min(n, s + max(1, b)))
        elif kind == "rem":
            if n > 0:
                s = min(a, n - 1)
                r.remove_range(s, min(n, s + max(1, b)))
        elif kind == "ann":
            if n > 0:
                s = min(a, n - 1)
                r.annotate_range(s, min(n, s + max(1, b)), {"k": b})
    factory.process_all_messages()
    texts = {r.text for r in reps}
    assert len(texts) == 1, f"diverge: {texts}"
    digests = {r.summarize().digest() for r in reps}
    assert len(digests) == 1, "summary digests diverge"


def test_deep_lag_pending_obliterate_prediction():
    """Fuzz-minimized: a replica with a PENDING obliterate must predict
    the kill of an arriving concurrent insert, or its follow-up ops count
    text no remote view contains."""
    _run_lag_script(
        [("op", 0, "ins", 0, 2), ("sync", 99), ("op", 1, "ins", 6, 3),
         ("op", 1, "ins", 1, 1), ("op", 0, "ob", 0, 2), ("sync", 2),
         ("op", 0, "ins", 8, 1)],
        n_clients=2,
    )


def test_deep_lag_overlapping_obliterates():
    """Fuzz-minimized: overlapping concurrent obliterates — the zero-width
    pass must resolve positions in the pristine pre-op view on the apply
    AND ack paths, and prediction-joined losers stay zero-width slots."""
    _run_lag_script(
        [("op", 0, "ins", 0, 4), ("sync", 99), ("op", 1, "ins", 2, 1),
         ("op", 1, "ob", 0, 3), ("op", 2, "ob", 0, 4)],
        n_clients=3,
    )


def test_deep_lag_obliterate_stamp_involvement():
    """Fuzz-minimized: an obliterate stamp makes its author involved in
    the tombstone's visibility — annotate resolution in the author's name
    must hide slots the author's obliterate covered even when an earlier
    remove won the removal."""
    _run_lag_script(
        [("op", 0, "ins", 0, 4), ("sync", 99), ("op", 0, "rem", 2, 1),
         ("op", 1, "ann", 0, 1), ("op", 0, "ins", 6, 2),
         ("op", 1, "ins", 7, 4), ("op", 1, "ob", 6, 3),
         ("op", 0, "ins", 1, 3), ("op", 0, "ob", 4, 3), ("sync", 4),
         ("op", 0, "ann", 10, 3)],
        n_clients=2,
    )


def test_deep_lag_fuzz_random_partial_delivery():
    """Seeded sweep of random partial-delivery interleavings with
    obliterate in the mix (the deep-lag shape that diverged before the
    round-3 hardening; 40k-seed sweeps ran clean offline)."""
    import random as _random

    for seed in range(300):
        rng = _random.Random(seed * 31 + 7)
        nc = rng.choice([2, 3])
        script = [("op", 0, "ins", 0, 4), ("sync", 99)]
        for _ in range(rng.randint(5, 14)):
            if rng.random() < 0.25:
                script.append(("sync", rng.randint(1, 4)))
            else:
                script.append(
                    ("op", rng.randint(0, nc - 1),
                     rng.choice(["ins", "ins", "ob", "rem", "ann"]),
                     rng.randint(0, 10), rng.randint(1, 4)))
        _run_lag_script(script, nc)


def test_deep_lag_fuzz_full_spec_with_device_parity():
    """Deep-lag fuzz through the full harness (annotate+intervals+
    obliterate, partial delivery) with the device kernel replaying the
    same log to byte-identical summaries."""
    from fluidframework_tpu.ops.mergetree_kernel import (
        MergeTreeDocInput,
        replay_mergetree_batch,
    )
    from fluidframework_tpu.testing.fuzz import StringFuzzSpec, run_fuzz
    from fluidframework_tpu.testing.mocks import channel_log

    for seed in range(12):
        replicas, factory = run_fuzz(
            StringFuzzSpec(annotate=True, intervals=True, obliterate=True),
            seed=20000 + seed, n_clients=4, rounds=18,
        )
        doc = MergeTreeDocInput(
            "fuzz", ops=channel_log(factory, "fuzz"),
            final_seq=factory.sequencer.seq,
            final_msn=factory.sequencer.min_seq,
        )
        [device] = replay_mergetree_batch([doc])
        assert device.digest() == replicas[0].summarize().digest(), seed
