"""Streaming fold (ISSUE 16): sequencer-attached incremental
summarization with device-resident doc state.

Covers the StreamFoldService poll loop (cadence, publish, stall/crash
seams), the StreamHeadIndex publication map, the server's streaming-head
catch-up lane, the pinned resident-state tier of DevicePackCache, the
scenario-spec fail-loud validation for the real-caller election bound,
and on-vs-off byte identity of the folded summaries.
"""

import pytest

from fluidframework_tpu.protocol.messages import MessageType, RawOperation
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.catchup import CatchupService
from fluidframework_tpu.service.catchup_cache import StreamHeadIndex
from fluidframework_tpu.service.orderer import LocalOrderingService
from fluidframework_tpu.service.server import OrderingServer
from fluidframework_tpu.service.streamfold import StreamFoldService
from fluidframework_tpu.testing.faults import (
    FaultInjector, FaultPlan, FaultPoint,
)


def _seed_tree():
    rt = ContainerRuntime()
    rt.create_datastore("ds").create_channel("sequence-tpu", "text")
    return rt.summarize()


def _service_with_docs(n_docs=2, oplog=None):
    service = LocalOrderingService(oplog=oplog)
    tree = _seed_tree()
    ids = []
    for i in range(n_docs):
        doc_id = f"sf-{i:02d}"
        service.storage.upload(doc_id, tree, 0)
        service.create_document(doc_id)
        ids.append(doc_id)
    return service, ids


def _type(service, doc_id, n, client="c1"):
    """Submit n single-char inserts through the real endpoint."""
    ep = service.endpoint(doc_id)
    if client not in ep._orderer.sequencer._slots:
        ep.connect(client)  # the JOIN takes one sequence number
    ref = service.oplog.head(doc_id)
    start = 0
    for msg in service.oplog.get(doc_id):
        if msg.client_id == client:
            start = max(start, msg.client_seq)
    for i in range(n):
        msg = ep.submit(RawOperation(
            client_id=client, client_seq=start + i + 1, ref_seq=ref,
            type=MessageType.OP,
            contents={"type": "groupedBatch", "ops": [
                {"ds": "ds", "channel": "text",
                 "clientSeq": start + i + 1,
                 "contents": {"kind": "insert", "pos": 0, "text": "a"}}]},
        ))
        ref = msg.seq
    return ref


# -- StreamHeadIndex ---------------------------------------------------------


def test_head_index_publish_is_monotone_and_epoch_pinned():
    idx = StreamHeadIndex()
    assert idx.publish("d", "h1", 10, "e1")
    assert idx.get("d", "e1") == ("h1", 10)
    # Stale ref_seq never regresses the published head.
    assert not idx.publish("d", "h0", 5, "e1")
    assert idx.get("d", "e1") == ("h1", 10)
    assert idx.counters.get("regressions") == 1
    # A different epoch sweeps the map: old entries are unservable.
    assert idx.get("d", "e2") is None
    assert idx.publish("d", "h2", 12, "e2")
    assert idx.get("d", "e2") == ("h2", 12)
    assert len(idx) == 1


def test_head_index_lag_high_water():
    idx = StreamHeadIndex()
    idx.publish("d", "h1", 10, "e")
    assert idx.observe_lag("d", 14) == 4
    assert idx.observe_lag("d", 11) == 1
    assert idx.stats()["lag_max"] == 4
    # Never-published doc: the whole head is lag.
    assert idx.observe_lag("x", 7) == 7


# -- the streaming poll loop -------------------------------------------------


def test_poll_folds_at_cadence_and_publishes():
    service, (d0, d1) = _service_with_docs()
    catchup = CatchupService(service, mesh=None)
    sf = StreamFoldService(service, catchup, cadence_ops=4,
                           retention_floor=64, truncate=False).attach()
    _type(service, d0, 5)
    _type(service, d1, 2)  # below cadence: stays pending
    assert sf.due() == [d0]
    results = sf.poll()
    assert set(results) == {d0}
    handle, ref_seq = results[d0]
    assert ref_seq == service.oplog.head(d0)
    # Published through the index AND durable in the store.
    assert sf.head_index.get(d0, service.storage.epoch) == (handle, ref_seq)
    assert service.storage.read(handle) is not None
    assert sf.counters["publishes"] == 1
    assert sf.counters["ops_folded"] >= 5
    # force folds the sub-cadence doc too.
    assert set(sf.poll(force=True)) == {d1}
    # Nothing pending → an empty round.
    assert sf.poll(force=True) == {}
    assert sf.stats()["pending_docs"] == 0


def test_commit_hook_records_without_folding():
    service, (d0, _d1) = _service_with_docs()
    catchup = CatchupService(service, mesh=None)
    sf = StreamFoldService(service, catchup, cadence_ops=2,
                           truncate=False).attach()
    _type(service, d0, 3)
    # The hook only RECORDED: no fold happened during stamping.
    assert sf.counters["folds"] == 0
    assert sf.stats()["pending_docs"] == 1
    sf.detach()
    _type(service, d0, 2)
    # Detached: commits after detach are invisible.
    assert sf.due(force=True) == [d0]
    heads = dict(sf._pending)
    assert heads[d0] == 4  # JOIN + 3 ops; the 2 post-detach ops unseen


def test_stall_skips_round_and_crash_aborts_mid_selection():
    plan = FaultPlan(seed=0, points=(
        FaultPoint("stream.stall", "stall", at=1),
        FaultPoint("stream.crash", "fail", at=1),
    ))
    faults = FaultInjector(plan)
    service, (d0, d1) = _service_with_docs()
    catchup = CatchupService(service, mesh=None)
    sf = StreamFoldService(service, catchup, cadence_ops=2,
                           truncate=False, faults=faults).attach()
    _type(service, d0, 3)
    _type(service, d1, 3)
    # Round 1 stalls whole: nothing folds, both docs stay pending.
    assert sf.poll() == {}
    assert sf.counters["stalls"] == 1
    assert sf.stats()["pending_docs"] == 2
    # Round 2 crashes mid-selection on the FIRST doc: the round dies,
    # both docs survive to fold next round (swallowed + counted).
    assert sf.poll() == {}
    assert sf.counters["crashes"] == 1
    assert sf.stats()["pending_docs"] == 2
    # Round 3 is clean: both fold, byte-identical to a cold fold.
    results = sf.poll()
    assert set(results) == {d0, d1}
    assert not faults.unfired()


def test_streaming_matches_cold_fold_byte_identically():
    # Twin corpora: one folds continuously via streaming, the other cold
    # at the end — same bytes (the SAME CatchupService fold either way).
    stream_svc, (sd,) = _service_with_docs(n_docs=1)
    cold_svc, (cd,) = _service_with_docs(n_docs=1)
    catchup = CatchupService(stream_svc, mesh=None)
    sf = StreamFoldService(stream_svc, catchup, cadence_ops=4,
                           truncate=False).attach()
    for _ in range(4):
        _type(stream_svc, sd, 4)
        sf.poll()
    _type(cold_svc, cd, 16)
    cold = CatchupService(cold_svc, mesh=None).catch_up([cd], upload=False)
    handle, ref_seq = sf.head_index.get(sd, stream_svc.storage.epoch)
    assert ref_seq == 17 and cold[cd][1] == 17  # JOIN + 16 ops each
    # upload=False hands back the fold's content digest, not a store
    # handle — exactly the byte-identity token we want to compare.
    assert stream_svc.storage.read(handle).digest() == cold[cd][0]


# -- summary-anchored truncation via the poll loop ---------------------------


def test_poll_truncates_behind_summary_with_retention_floor():
    service, (d0,) = _service_with_docs(n_docs=1)
    catchup = CatchupService(service, mesh=None)
    sf = StreamFoldService(service, catchup, cadence_ops=4,
                           retention_floor=4).attach()
    _type(service, d0, 16)  # head 17: JOIN + 16 ops
    results = sf.poll()
    assert results[d0][1] == 17
    # cut = min(summary ref 17, MSN, head 17 − retention 4 = 13)
    floor = service.oplog.floor(d0)
    assert 0 < floor <= 13
    assert sf.counters["truncations"] == 1
    assert sf.counters["truncated_msgs"] == floor
    # Boundary gap-repair read stays legal; below raises.
    tail = service.oplog.get(d0, from_seq=floor)
    assert [m.seq for m in tail] == list(range(floor + 1, 18))
    from fluidframework_tpu.service.oplog import TruncatedRangeError
    with pytest.raises(TruncatedRangeError):
        service.oplog.get(d0, from_seq=floor - 1)
    # The truncated doc still catches up byte-identically (summary+tail).
    again = CatchupService(service, mesh=None).catch_up([d0], upload=False)
    assert again[d0][1] == 17


# -- the server's streaming-head lane ----------------------------------------


class _Session:
    client_id = "storm"
    authenticated = True
    tenant = None


def test_server_stream_lane_serves_published_head():
    service, (d0,) = _service_with_docs(n_docs=1)
    server = OrderingServer(service)
    sf = server.enable_streaming(cadence_ops=4, retention_floor=64)
    _type(service, d0, 8)  # head 9: JOIN + 8 ops
    folded = server._dispatch(_Session(), "stream_poll", {})
    assert folded["folded"][d0][1] == 9
    # Two more ops — within the stream lag: served from the streaming
    # head with NO fold, lane marked, admission counter bumped.
    _type(service, d0, 2)
    before = server.admission.get("catchup.stream")
    out = server._dispatch(_Session(), "catchup", {"docs": [d0]})
    assert out["lane"] == "stream"
    assert out["stream"] == [d0]
    assert out["docs"][d0][1] == 9  # the published ref_seq, tail repairs
    assert server.admission.get("catchup.stream") == before + 1
    # The served handle resolves and the tail read is available.
    handle, ref_seq = out["docs"][d0]
    assert service.storage.read(handle) is not None
    assert [m.seq for m in service.oplog.get(d0, from_seq=ref_seq)] \
        == [10, 11]
    assert sf.stats()["head_publishes"] >= 1


def test_server_stream_lane_degrades_when_lag_exceeds_cadence():
    service, (d0,) = _service_with_docs(n_docs=1)
    server = OrderingServer(service)
    server.enable_streaming(cadence_ops=4, retention_floor=64)
    _type(service, d0, 8)  # head 9: JOIN + 8 ops
    server._dispatch(_Session(), "stream_poll", {})
    # The summary ages: 6 > cadence unfolded ops — the stream lane must
    # NOT serve a stale head; the request falls through to the ordinary
    # fold path and answers at the true head.
    _type(service, d0, 6)
    out = server._dispatch(_Session(), "catchup", {"docs": [d0]})
    assert out["lane"] != "stream"
    assert out["docs"][d0][1] == 15


# -- pinned resident-state tier (DevicePackCache) ----------------------------


def _pack_chunk(i, ops=6):
    import bench
    from fluidframework_tpu.ops.mergetree_kernel import pack_mergetree_batch

    docs = [bench.synth_doc(i * 16 + j, ops) for j in range(2)]
    for j, doc in enumerate(docs):
        # Synthetic identity tokens (bench docs have none and would
        # bypass the cache): same shape as the real (epoch, channel,
        # ref, head) tuples.
        doc.cache_token = ("e0", f"chunk{i}-doc{j}", 0, ops)
        doc.binary_ops = None
    state, packed_ops, meta = pack_mergetree_batch(docs)
    return state, packed_ops, meta


def test_device_cache_pin_survives_lru_sweep():
    from fluidframework_tpu.ops.device_cache import DevicePackCache

    cache = DevicePackCache(max_bytes=192 << 20, pin_max_bytes=64 << 20)
    state, ops, meta = _pack_chunk(0)
    cache.acquire(state, ops, meta, pin=True)
    one_entry = cache.stats()["bytes"]
    assert cache.stats()["pinned_entries"] == 1
    # Shrink the device budget so two entries cannot coexist: the LRU
    # sweep may only take UNPINNED entries — the pinned one survives
    # even over-budget.
    cache.max_bytes = one_entry + 1
    state2, ops2, meta2 = _pack_chunk(1)
    cache.acquire(state2, ops2, meta2)
    stats = cache.stats()
    assert stats["pinned_entries"] == 1
    assert any(e.pinned for e in cache._entries.values())
    # Control: with the first entry unpinned, the same pressure sweeps
    # it out.
    ctrl = DevicePackCache(max_bytes=one_entry + 1,
                           pin_max_bytes=64 << 20)
    ctrl.acquire(state, ops, meta)
    ctrl.acquire(state2, ops2, meta2)
    assert ctrl.stats()["evictions"] >= 1


def test_device_cache_pin_budget_spills_to_host_and_restores():
    from fluidframework_tpu.ops.device_cache import DevicePackCache

    cache = DevicePackCache(max_bytes=192 << 20, pin_max_bytes=1)
    state, ops, meta = _pack_chunk(2)
    cache.acquire(state, ops, meta, pin=True)
    # Pin budget is 1 byte: the pinned entry spills to host copies.
    stats = cache.stats()
    assert stats["spills"] >= 1
    assert stats["pinned_bytes"] == 0
    assert stats["spilled_bytes"] > 0
    # Re-acquire restores the spilled entry (h2d) and serves it.
    cache.pin_max_bytes = 64 << 20
    cache.acquire(state, ops, meta, pin=True)
    assert cache.stats()["unspills"] >= 1


def test_device_cache_unpin_returns_entry_to_lru():
    from fluidframework_tpu.ops.device_cache import DevicePackCache

    cache = DevicePackCache(max_bytes=192 << 20, pin_max_bytes=64 << 20)
    state, ops, meta = _pack_chunk(3)
    cache.acquire(state, ops, meta, pin=True)
    tokens = next(iter(cache._entries))
    assert cache.unpin(tokens)
    assert cache.stats()["pinned_entries"] == 0
    assert not cache.unpin(tokens)  # already unpinned
    assert not cache.pin(("nope",))  # unknown tokens


# -- scenario-spec fail-loud validation (the PR 15 debt satellite) -----------


def test_spec_rejects_gate_beyond_real_caller_bound():
    from fluidframework_tpu.testing.scenarios import build_scenario
    import dataclasses

    spec = build_scenario("catchup-storm", seed=0, clients=64, docs=4,
                          shards=1)
    with pytest.raises(ValueError, match="silently bounds the election"):
        dataclasses.replace(spec, storm_min_cohort=8,
                            storm_clients_per_doc=4)
    # Declaring a floor the bound admits is fine.
    ok = dataclasses.replace(spec, storm_min_cohort=4)
    assert ok.storm_min_cohort == 4


def test_spec_rejects_stream_without_storm_server():
    from fluidframework_tpu.testing.scenarios import build_scenario
    import dataclasses

    spec = build_scenario("steady-typing", seed=0, clients=64, docs=4,
                          shards=1)
    with pytest.raises(ValueError, match="storm=True"):
        dataclasses.replace(spec, stream=True)


def test_truncation_never_cuts_above_msn():
    # A connected client pinned at an old ref_seq holds MSN down: the
    # cut must stay at/below MSN so the client's gap repair still finds
    # its records.
    service, (d0,) = _service_with_docs(n_docs=1)
    ep = service.endpoint(d0)
    ep.connect("slow")
    _type(service, d0, 16, client="typer")
    ep.update_ref_seq("slow", 3)
    catchup = CatchupService(service, mesh=None)
    sf = StreamFoldService(service, catchup, cadence_ops=4,
                           retention_floor=0).attach()
    sf.note_doc(d0)
    sf.poll(force=True)
    msn = ep._orderer.sequencer.min_seq
    assert service.oplog.floor(d0) <= msn
    # The slow client's repair from its own ref view still reads.
    assert service.oplog.get(d0, from_seq=msn) is not None
