"""Telemetry: logger tree, performance events, config gates, and the
loader/catchup integration points."""

import io

import pytest

from fluidframework_tpu.utils.telemetry import (
    CollectingLogger,
    ConfigProvider,
    CounterSet,
    LockedCounterSet,
    MonitoringContext,
    PerformanceEvent,
    StreamLogger,
    create_child_logger,
)


def test_counter_delta_subtracts_an_earlier_snapshot():
    counters = CounterSet("a", "b")
    counters.bump("a", 2)
    since = counters.snapshot()
    counters.bump("a")
    counters.bump("b", 3)
    counters.bump("c", 4)  # counter born after the snapshot
    assert counters.delta(since) == {"a": 1, "b": 3, "c": 4}
    # zero-delta counters are dropped, not reported as 0
    assert "a" not in counters.delta(counters.snapshot())
    # a fresh snapshot against itself is empty
    assert counters.delta(counters.snapshot()) == {}


def test_counter_delta_rejects_a_foreign_snapshot():
    counters = CounterSet("a")
    other = CounterSet("a")
    other.bump("a", 5)
    with pytest.raises(ValueError):
        counters.delta(other.snapshot())


def test_locked_counter_delta_inherits_consistent_snapshot():
    counters = LockedCounterSet("x")
    since = counters.snapshot()
    counters.bump("x", 7)
    assert counters.delta(since) == {"x": 7}


def test_child_logger_namespaces_and_properties():
    sink = CollectingLogger()
    child = create_child_logger(sink, "loader", {"docId": "d1"})
    grandchild = create_child_logger(child, "deltaManager")
    grandchild.send({"eventName": "connect", "attempt": 1})
    [ev] = sink.events
    assert ev["eventName"] == "loader:deltaManager:connect"
    assert ev["docId"] == "d1" and ev["attempt"] == 1


def test_performance_event_end_and_cancel():
    sink = CollectingLogger()
    with PerformanceEvent.timed_exec(sink, "phase", k="v") as perf:
        perf["extra"]["items"] = 3
    names = [e["eventName"] for e in sink.events]
    assert names == ["phase_start", "phase_end"]
    assert sink.events[1]["items"] == 3
    assert sink.events[1]["durationMs"] >= 0

    with pytest.raises(ValueError):
        with PerformanceEvent.timed_exec(sink, "bad"):
            raise ValueError("boom")
    assert sink.events[-1]["eventName"] == "bad_cancel"
    assert "boom" in sink.events[-1]["error"]


def test_stream_logger_writes_json_lines():
    buf = io.StringIO()
    StreamLogger(buf).send({"eventName": "x", "n": 1})
    assert '"eventName": "x"' in buf.getvalue()


def test_config_provider_layers_and_types(monkeypatch):
    monkeypatch.setenv("FLUID_TPU_FLUID_GC_ENABLED", "false")
    cfg = ConfigProvider({"Fluid.Chunk.Size": "1024"})
    assert cfg.get_int("Fluid.Chunk.Size") == 1024
    assert cfg.get_bool("Fluid.Gc.Enabled", default=True) is False
    assert cfg.get_str("Fluid.Missing", "fallback") == "fallback"
    assert cfg.get_bool("Fluid.Missing", default=True) is True


def test_monitoring_context_threads_through_loader():
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService

    sink = CollectingLogger()
    mc = MonitoringContext(sink)
    service = LocalOrderingService()
    loader = Loader(LocalDocumentServiceFactory(service), mc=mc)

    def build(rt):
        rt.create_datastore("ds").create_channel("sequence-tpu", "t")

    a = loader.create("doc", "alice", build)
    a.runtime.get_datastore("ds").get_channel("t").insert_text(0, "x")
    a.drain()
    loader.resolve("doc", "bob")
    names = [e["eventName"] for e in sink.events]
    assert "loader:containerLoad_start" in names
    assert "loader:containerLoad_end" in names

    CatchupService(service, mc=mc).catch_up()
    names = [e["eventName"] for e in sink.events]
    assert "catchup:bulkCatchup_end" in names
    end = [e for e in sink.events
           if e["eventName"] == "catchup:bulkCatchup_end"][-1]
    assert end["docs"] == 1


def test_catchup_profile_gate_writes_xprof_trace(tmp_path):
    """The Catchup.ProfileDir config gate wraps each bulk fold in a JAX
    profiler trace (the per-replay-batch xprof hook of the telemetry
    design); without the gate, no profiler is ever loaded."""
    import os

    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService
    from fluidframework_tpu.utils.telemetry import (
        ConfigProvider,
        MonitoringContext,
    )

    service = LocalOrderingService()
    ep = service.create_document("doc")
    rt = ContainerRuntime()
    ds = rt.create_datastore("ds")
    text = ds.create_channel("sequence-tpu", "t")
    rt.connect(ep, "a")
    rt.drain()
    service.storage.upload("doc", rt.summarize(), rt.ref_seq)
    text.insert_text(0, "profile me")
    rt.drain()

    prof_dir = str(tmp_path / "xprof")
    mc = MonitoringContext(
        config=ConfigProvider({"Catchup.ProfileDir": prof_dir})
    )
    svc = CatchupService(service, mc=mc)
    out = svc.catch_up(["doc"])
    assert "doc" in out
    found = [
        f for _dir, _dirs, files in os.walk(prof_dir) for f in files
    ]
    assert any(f.endswith(".xplane.pb") for f in found), found

    # ungated: still folds, and the trace directory stays untouched
    before = sorted(
        f for _d, _ds, files in os.walk(prof_dir) for f in files
    )
    svc2 = CatchupService(service)
    assert svc2.catch_up(["doc"]) is not None
    after = sorted(
        f for _d, _ds, files in os.walk(prof_dir) for f in files
    )
    assert after == before
