"""Crash-consistency sweeps for the temp-write→publish paths.

The static side of this contract lives in fluidlint's durability family
(FL-DUR-RENAME / FL-DUR-COMMIT); these tests are the dynamic half: an
ALICE-style sweep that simulates a crash at EVERY byte offset of the
summary-object publish, plus ordering regressions for the two fsync
fixes the analyzer found (file_driver._store published without fsync;
native_pack._build_library published g++'s artifact without reopening
and fsyncing it).
"""

import os

import pytest

from fluidframework_tpu.drivers.file_driver import FileSummaryStorage
from fluidframework_tpu.ops import native_pack
from fluidframework_tpu.protocol.summary import SummaryTree


def _tree() -> SummaryTree:
    tree = SummaryTree()
    tree.add_blob("payload", b"durability sweep payload")
    sub = tree.add_tree("sub")
    sub.add_blob("x", b"nested blob")
    return tree


def test_summary_publish_crash_sweep_every_offset(tmp_path):
    """Simulate a crash after every byte of the tmp write, before the
    rename: the torn tmp must never be visible to reads, must be swept
    on reopen, and a re-upload must heal the handle byte-identically."""
    ref_root = str(tmp_path / "ref")
    handle = FileSummaryStorage(ref_root).upload("d", _tree(), 1)
    data = open(os.path.join(ref_root, "objects", handle), "rb").read()
    assert data, "reference object is empty — sweep would be vacuous"
    for offset in range(len(data) + 1):
        root = str(tmp_path / f"at{offset:04d}")
        FileSummaryStorage(root)  # lay down the store skeleton
        objects = os.path.join(root, "objects")
        torn = os.path.join(objects, f"{handle}.tmp.999.1")
        with open(torn, "wb") as f:
            f.write(data[:offset])
        reopened = FileSummaryStorage(root)
        # swept, invisible, unreadable — the handle simply doesn't exist
        assert not [n for n in os.listdir(objects) if ".tmp." in n], offset
        assert reopened.head("d") is None, offset
        with pytest.raises(KeyError):
            reopened.read(handle)
        # the retry heals: same content-addressed handle, readable tree
        assert reopened.upload("d", _tree(), 1) == handle, offset
        assert reopened.read(handle).digest() == handle, offset


def test_store_fsyncs_before_publish(tmp_path, monkeypatch):
    """Regression for the FL-DUR-RENAME true positive: every os.replace
    that publishes a summary object must be preceded by an os.fsync of
    the tmp bytes (a crash straight after the rename must not be able to
    publish an empty or torn object)."""
    storage = FileSummaryStorage(str(tmp_path / "store"))
    events = []
    real_fsync, real_replace = os.fsync, os.replace

    def rec_fsync(fd):
        events.append(("fsync", None))
        real_fsync(fd)

    def rec_replace(src, dst):
        events.append(("replace", src))
        real_replace(src, dst)

    monkeypatch.setattr(os, "fsync", rec_fsync)
    monkeypatch.setattr(os, "replace", rec_replace)
    storage.upload("d", _tree(), 1)
    publishes = [i for i, (kind, src) in enumerate(events)
                 if kind == "replace" and ".tmp." in str(src)]
    assert publishes, "upload published no object — recording broke"
    prev = -1
    for i in publishes:
        assert any(kind == "fsync" for kind, _ in events[prev + 1:i]), (
            f"object publish at event {i} had no fsync since the "
            f"previous publish: {events[:i + 1]}")
        prev = i


def test_native_pack_fsyncs_artifact_before_publish(tmp_path, monkeypatch):
    """Regression for the second FL-DUR-RENAME true positive: g++ writes
    the .so through its own descriptors, so _build_library must reopen
    and fsync the artifact before the publishing rename."""
    native = tmp_path / "native"
    native.mkdir()
    src = native / "oppack.cpp"
    src.write_text("// fake source\n")
    monkeypatch.setattr(native_pack, "_REPO_ROOT", str(tmp_path))
    monkeypatch.setattr(native_pack, "_SRC", str(src))

    def fake_gxx(cmd, **kwargs):
        out = cmd[cmd.index("-o") + 1]
        with open(out, "wb") as f:
            f.write(b"\x7fELF fake shared object")

    monkeypatch.setattr(native_pack.subprocess, "run", fake_gxx)
    events = []
    real_fsync, real_replace = os.fsync, os.replace

    def rec_fsync(fd):
        events.append(("fsync", None))
        real_fsync(fd)

    def rec_replace(src_path, dst):
        events.append(("replace", src_path))
        real_replace(src_path, dst)

    monkeypatch.setattr(os, "fsync", rec_fsync)
    monkeypatch.setattr(os, "replace", rec_replace)
    lib = native_pack._build_library()
    assert lib is not None and os.path.exists(lib)
    kinds = [kind for kind, _ in events]
    assert "replace" in kinds, "library was never published"
    publish = kinds.index("replace")
    assert ".tmp" in str(events[publish][1])
    assert "fsync" in kinds[:publish], (
        f"artifact published without an fsync first: {events}")
