"""Async front door (ISSUE 18): event-loop frame pump, shared-nothing
front-door replicas, and the direct-to-shard data path.

Four layers of coverage:

1. Pump mechanics: incremental ``[len][json]`` reassembly over arbitrary
   chunk boundaries, a live event-loop echo round-trip with pipelined
   frames, and the relay-budget accounting contract on a single
   ``PumpConnection`` (oversized-frame-into-empty-queue acceptance,
   over-budget rejection, budget-exempt priority frames).
2. Replica-death drills against the REAL wire (thread-backend shards —
   identical RPC and on-disk layout): a catch-up storm and a failover
   drill each run through TWO front-door replicas with the
   traffic-bearing one killed mid-run; clients fail over through the
   survivor and both runs land byte-identical to the fault-free
   single-replica oracle twin AND replay bit-identically — storm
   verdicts included, because out-of-proc admission now rides the wire
   clock (the shed Nack carries the admission snapshot the harness
   re-derives ``retry_after`` from).
3. The direct-to-shard path: clients resolve placement through the
   door's ``locate`` and tap the owning shardhost itself — the door's
   relay counter stays pinned at ZERO while every event arrives, and
   the control plane fails over across doors independently.
4. Direct clients ride a SHARD failover: the owner dies, the driver's
   next call hits the fence, re-resolves through the door, and
   continues against the adopting shard with the log contiguous.
"""

import dataclasses
import json
import socket
import struct
import time

import pytest

from fluidframework_tpu.drivers.network_driver import (
    NetworkDocumentServiceFactory,
)
from fluidframework_tpu.protocol.messages import MessageType, RawOperation
from fluidframework_tpu.protocol.wire import WIRE_VERSION, frame_bytes
from fluidframework_tpu.runtime.container import ContainerRuntime
from fluidframework_tpu.service.framepump import (
    FrameParser, FramePump, PumpConnection,
)
from fluidframework_tpu.service.frontdoor import FrontDoor
from fluidframework_tpu.testing.faults import FaultPlan, FaultPoint
from fluidframework_tpu.testing.scenarios import (
    build_scenario, oracle_spec, run_swarm,
)


# -- 1. pump mechanics --------------------------------------------------------


def test_frame_parser_reassembles_across_arbitrary_chunks():
    frames = [b"a", b"bb" * 10, json.dumps({"k": 1}).encode()]
    wire = b"".join(struct.pack(">I", len(f)) + f for f in frames)
    parser = FrameParser()
    out = []
    for i in range(0, len(wire), 3):  # dribble in 3-byte chunks
        out.extend(parser.feed(wire[i:i + 3]))
    assert out == frames
    # one chunk carrying many frames plus a tail kept for the next feed
    parser = FrameParser()
    out = parser.feed(wire + struct.pack(">I", 5) + b"xy")
    assert out == frames
    assert parser.feed(b"z" * 3) == [b"xyzzz"]


def test_frame_parser_rejects_oversized_frame():
    from fluidframework_tpu.protocol.wire import MAX_FRAME

    parser = FrameParser()
    with pytest.raises(ValueError):
        parser.feed(struct.pack(">I", MAX_FRAME + 1))


def test_frame_pump_echo_round_trip_pipelined():
    """One event-loop thread owns accept + read + write: pipelined
    requests on one socket all come back (matched by ``re``), and the
    pump counts the accept."""
    def echo(conn, frame):
        conn.send_obj({"re": frame["id"], "echo": frame["params"]})

    pump = FramePump("127.0.0.1", 0, echo).start()
    try:
        with socket.create_connection(("127.0.0.1", pump.port),
                                      timeout=10) as sock:
            for rid in range(8):  # pipelined: all writes before reads
                sock.sendall(frame_bytes(
                    {"v": WIRE_VERSION, "id": rid, "params": {"n": rid}}))
            parser, got = FrameParser(), []
            while len(got) < 8:
                got.extend(json.loads(p) for p in parser.feed(
                    sock.recv(64 << 10)))
            assert sorted(f["re"] for f in got) == list(range(8))
            assert all(f["echo"] == {"n": f["re"]} for f in got)
        assert pump.accepted == 1
    finally:
        pump.close()


def test_pump_connection_relay_budget_contract():
    """The PR 15 relay contract on the pump's write buffers: a frame
    larger than the whole budget is still accepted into an EMPTY queue
    (serialize-once means huge snapshots must pass), the next frame over
    budget is refused (caller demotes), and priority control frames are
    budget-exempt."""
    pump = FramePump("127.0.0.1", 0, lambda c, f: None)  # never started
    a, b = socket.socketpair()
    try:
        conn = PumpConnection(a, pump, relay_budget=8)
        assert conn.relay(b"x" * 64)          # oversized but queue empty
        assert not conn.relay(b"y")           # over budget: demote me
        conn.relay_priority(b"demoted!")      # control frames are exempt
        assert conn.relay_pending() == 64     # priority bytes uncharged
    finally:
        a.close()
        b.close()
        pump.close()


# -- 2. replica-death drills --------------------------------------------------


def _replica_drill(name, tmp_path, extra_points=()):
    spec = build_scenario(name, seed=7, clients=400, docs=8, shards=2)
    total = sum(p.ticks for p in spec.phases)
    plan = FaultPlan(seed=7, points=tuple(extra_points) + (
        FaultPoint("replica.kill", "kill", at=total // 2),))
    return dataclasses.replace(
        spec, out_of_proc=True, proc_spawn="thread", replicas=2,
        plan=plan, sample_every=4, dir=str(tmp_path / "swarm"))


def test_replica_death_storm_drill_oracle_and_replay(tmp_path):
    """Catch-up storm through two shared-nothing replicas, the
    traffic-bearing one killed mid-run: the swarm fails over through
    the survivor, converges byte-identical to the single-replica
    oracle, and the whole run — storm shed/retry verdicts included —
    replays bit-identically off the wire-clock admission snapshots."""
    spec = _replica_drill("catchup-storm", tmp_path)
    result = run_swarm(spec)
    assert result.replica_kills, "replica kill never executed"
    assert result.shard_stats["door_failovers"] >= 1
    assert result.shard_stats["doors"] == 2
    storm = result.storm
    assert storm["wire_clock"] is True
    assert storm["served"] == storm["requests"] > 0
    # the verdict counters live in the IDENTITY surface now, not in a
    # wall-clock-excluded remote bucket
    assert "swarm.storm_shed" in result.counters
    twin = run_swarm(oracle_spec(spec, result))
    assert result.sampled_digests == twin.sampled_digests
    assert result.per_doc_head == twin.per_doc_head
    replay = run_swarm(dataclasses.replace(
        spec, dir=str(tmp_path / "swarm2")))
    assert replay.identity() == result.identity()


def test_replica_death_failover_drill_with_shard_kill(tmp_path):
    """The failover drill with BOTH faults live: a shard dies (epoch
    fence + adoption from its log) and a front-door replica dies
    (client-side door failover) in the same run — still byte-identical
    to the fault-free single-shard, single-replica twin."""
    spec = build_scenario("failover-drill", seed=7, clients=400, docs=8,
                          shards=2)
    shard_kills = tuple(p for p in spec.plan.points
                        if p.site == "shard.kill")
    assert shard_kills, "scenario lost its shard kill"
    spec = _replica_drill("failover-drill", tmp_path,
                          extra_points=shard_kills)
    result = run_swarm(spec)
    assert result.kills, "the shard kill never executed"
    assert result.replica_kills, "the replica kill never executed"
    twin = run_swarm(oracle_spec(spec, result))
    assert result.sampled_digests == twin.sampled_digests
    assert result.per_doc_head == twin.per_doc_head
    replay = run_swarm(dataclasses.replace(
        spec, dir=str(tmp_path / "swarm2")))
    assert replay.identity() == result.identity()


# -- 3 + 4. direct-to-shard ---------------------------------------------------


def _op(client, i, contents=None):
    return RawOperation(client_id=client, client_seq=i + 1, ref_seq=0,
                        type=MessageType.OP,
                        contents=contents or {"i": i})


def _wait(predicate, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


def test_direct_to_shard_pins_door_out_of_byte_path(tmp_path):
    """A ``direct=True`` driver resolves placement via the door's
    ``locate`` and taps the owning shardhost itself: every event
    arrives, while BOTH doors' relay counter (``fd.events``) stays
    pinned at zero — the door is control plane, not byte path.  Killing
    the replica the control plane rides proves doors fail over
    independently of the data path (storage reads ride the shard,
    untouched)."""
    door = FrontDoor(str(tmp_path / "proc"), n_shards=2, spawn="thread",
                     request_timeout=5.0).start()
    rep = FrontDoor(str(tmp_path / "proc"), spawn="attach",
                    attach_addrs=door.shard_addrs(),
                    request_timeout=5.0).start()
    try:
        factory = NetworkDocumentServiceFactory(
            port=rep.port, replicas=[("127.0.0.1", door.port)],
            direct=True)
        service = factory.create_document(
            "d-1", ContainerRuntime().summarize())
        endpoint = service.connection()
        got = []
        endpoint.subscribe(lambda m: got.append(m.seq))
        endpoint.connect("c1")
        for i in range(5):
            endpoint.submit(_op("c1", i))
        assert _wait(lambda: len(got) >= 6)  # 5 ops + the JOIN
        assert door.counters.get("fd.events") == 0
        assert rep.counters.get("fd.events") == 0
        assert factory._direct_rpcs["d-1"].shard is not None
        # control-plane door failover, data path untouched
        rep.kill()
        assert service.storage.latest()[0] is not None
        assert factory._rpc.request("ping", {}) == "pong"
        assert factory._rpc.failovers == 1
        factory.close()
    finally:
        if not rep.killed:
            rep.close()
        door.close()


def test_direct_client_rides_shard_failover_via_re_resolution(tmp_path):
    """The owning shard dies mid-session: the direct client's next call
    hits the fence/dead socket, re-resolves through the door, and lands
    on the adopting shard — ops keep sequencing, the subscription tap is
    re-established on the new owner, and the durable log stays
    contiguous across the adoption."""
    door = FrontDoor(str(tmp_path / "proc"), n_shards=2, spawn="thread",
                     request_timeout=5.0).start()
    try:
        factory = NetworkDocumentServiceFactory(port=door.port,
                                                direct=True)
        service = factory.create_document(
            "d-1", ContainerRuntime().summarize())
        endpoint = service.connection()
        got = []
        endpoint.subscribe(lambda m: got.append(m.seq))
        endpoint.connect("c1")
        for i in range(3):
            endpoint.submit(_op("c1", i))
        assert _wait(lambda: len(got) >= 4)
        owner = factory._direct_rpcs["d-1"].shard
        assert owner is not None
        door.fail_shard(owner)
        # the next data-plane calls re-resolve and ride the adopter
        for i in range(3, 6):
            endpoint.submit(_op("c1", i))
        assert _wait(lambda: len(got) >= 7), f"only {len(got)} events"
        assert factory._direct_rpcs["d-1"].shard != owner
        assert factory._direct_rpcs["d-1"].failovers >= 1
        assert door.contiguous(["d-1"]) == {"d-1": True}
        assert door.counters.get("fd.events") == 0
        factory.close()
    finally:
        door.close()
