"""Native (C++) op packing: binary codec round-trip, bit-identical arrays
vs the pure-Python pack path, byte-identical summaries end-to-end."""

import random

import numpy as np
import pytest

from fluidframework_tpu.dds.sequence import SharedString
from fluidframework_tpu.ops.interning import Interner
from fluidframework_tpu.ops.mergetree_kernel import (
    MergeTreeDocInput,
    pack_mergetree_batch,
    replay_mergetree_batch,
)
from fluidframework_tpu.ops.native_pack import (
    decode_string_ops,
    encode_string_ops,
    load_library,
    count_stream,
)
from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage


def synth_ops(seed, n_ops, unicode_text=False):
    rng = random.Random(seed)
    alphabet = "abçdé日本語 zz" if unicode_text else "abcdefgh "
    ops, length = [], 0
    for i in range(n_ops):
        seq = i + 1
        client = f"c{i % 3}"
        if length < 4 or rng.random() < 0.7:
            text = "".join(rng.choice(alphabet)
                           for _ in range(rng.randint(1, 6)))
            contents = {"kind": "insert", "pos": rng.randint(0, length),
                        "text": text}
            length += len(text)
        else:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 5))
            contents = {"kind": "remove", "start": start, "end": end}
            length -= end - start
        ops.append(SequencedMessage(
            seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
            min_seq=0, type=MessageType.OP, contents=contents,
        ))
    return ops


def test_native_library_builds():
    # g++ is in the image; the library must actually compile and load.
    assert load_library() is not None


def test_codec_roundtrip_including_unicode():
    ops = synth_ops(7, 40, unicode_text=True)
    clients = Interner()
    blob = encode_string_ops(ops, clients)
    n, text_bytes, text_chars = count_stream(blob)
    assert n == 40
    assert text_bytes >= text_chars  # multibyte chars present
    decoded = decode_string_ops(blob, list(clients.values))
    for orig, back in zip(ops, decoded):
        assert orig.seq == back.seq
        assert orig.client_id == back.client_id
        assert orig.contents == back.contents


@pytest.mark.parametrize("unicode_text", [False, True])
def test_native_pack_bit_identical_to_python(unicode_text):
    docs_py, docs_bin = [], []
    for d in range(6):
        ops = synth_ops(d, 30 + d, unicode_text=unicode_text)
        clients = Interner()
        blob = encode_string_ops(ops, clients)
        docs_py.append(MergeTreeDocInput(
            doc_id=f"doc{d}", ops=ops, final_seq=len(ops), final_msn=0))
        docs_bin.append(MergeTreeDocInput(
            doc_id=f"doc{d}", ops=[], binary_ops=blob,
            binary_clients=list(clients.values),
            final_seq=len(ops), final_msn=0))

    st_py, op_py, meta_py = pack_mergetree_batch(docs_py)
    st_bin, op_bin, meta_bin = pack_mergetree_batch(docs_bin)
    for name in op_py._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(op_py, name)),
            np.asarray(getattr(op_bin, name)), err_msg=name)
    for name in st_py._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(st_py, name)),
            np.asarray(getattr(st_bin, name)), err_msg=name)
    assert meta_py["arena"].finalize() == meta_bin["arena"].finalize()


def test_native_end_to_end_summary_byte_identity():
    docs = []
    oracles = []
    for d in range(4):
        ops = synth_ops(100 + d, 50)
        clients = Interner()
        blob = encode_string_ops(ops, clients)
        docs.append(MergeTreeDocInput(
            doc_id=f"doc{d}", ops=[], binary_ops=blob,
            binary_clients=list(clients.values),
            final_seq=len(ops), final_msn=0))
        replica = SharedString(f"doc{d}")
        for msg in ops:
            replica.process(msg, local=False)
        oracles.append(replica.summarize())

    summaries = replay_mergetree_batch(docs)
    for dev, oracle in zip(summaries, oracles):
        assert dev.digest() == oracle.digest()


def test_mixed_python_and_binary_docs_in_one_batch():
    ops_a = synth_ops(1, 25)
    clients = Interner()
    blob = encode_string_ops(ops_a, clients)
    doc_bin = MergeTreeDocInput(
        doc_id="bin", ops=[], binary_ops=blob,
        binary_clients=list(clients.values),
        final_seq=len(ops_a), final_msn=0)
    ops_b = synth_ops(2, 25)
    doc_py = MergeTreeDocInput(
        doc_id="py", ops=ops_b, final_seq=len(ops_b), final_msn=0)

    summaries = replay_mergetree_batch([doc_bin, doc_py])
    for doc_id, ops, summary in [("bin", ops_a, summaries[0]),
                                 ("py", ops_b, summaries[1])]:
        replica = SharedString(doc_id)
        for msg in ops:
            replica.process(msg, local=False)
        assert summary.digest() == replica.summarize().digest()


def test_native_extract_bodies_byte_identity_hostile_text():
    """C++ oppack_extract vs the per-slot Python extraction on streams with
    JSON-escape-needing text, unicode, props, annotates, and window expiry:
    the summary bytes must be identical (and match the oracle)."""
    import random as _random

    from fluidframework_tpu.dds.sequence import SharedString
    from fluidframework_tpu.ops.interning import Interner
    from fluidframework_tpu.ops.mergetree_kernel import (
        MergeTreeDocInput,
        replay_mergetree_batch,
    )
    from fluidframework_tpu.ops.native_pack import (
        encode_string_ops,
        load_library,
    )
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    assert load_library() is not None, "native library must build in CI"

    alphabet = ['"', "\\", "\n", "\t", "\x07", "é", "文", "𝄞", "a", "b ", "c"]
    docs = []
    for di in range(6):
        rng = _random.Random(1000 + di)
        ops, length = [], 0
        for i in range(40):
            seq = i + 1
            client = f"c{i % 3}"
            r = rng.random()
            if r < 0.6 or length < 4:
                text = "".join(
                    rng.choice(alphabet) for _ in range(rng.randint(1, 5))
                )
                contents = {"kind": "insert",
                            "pos": rng.randint(0, length), "text": text}
                length += len(text)
            elif r < 0.85:
                start = rng.randint(0, length - 2)
                end = min(length, start + rng.randint(1, 5))
                contents = {"kind": "remove", "start": start, "end": end}
                length -= end - start
            else:
                start = rng.randint(0, length - 2)
                end = min(length, start + rng.randint(1, 4))
                contents = {"kind": "annotate", "start": start, "end": end,
                            "props": {"style": rng.choice(
                                ["bold", "ital\"ic", None, 7])}}
            ops.append(SequencedMessage(
                seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
                min_seq=0, type=MessageType.OP, contents=contents,
            ))
        final_msn = 12 if di % 2 else 0   # exercise tombstone expiry
        if di < 3:
            # message-list path
            docs.append(MergeTreeDocInput(
                doc_id=f"h{di}", ops=ops, final_seq=40, final_msn=final_msn,
            ))
        else:
            # binary path WITH props (encoder-local intern tables)
            clients, keys, vals = Interner(), Interner(), Interner()
            blob = encode_string_ops(ops, clients, keys, vals)
            docs.append(MergeTreeDocInput(
                doc_id=f"h{di}", ops=[], binary_ops=blob,
                binary_clients=list(clients.values),
                binary_prop_keys=list(keys.values),
                binary_values=list(vals.values),
                final_seq=40, final_msn=final_msn,
            ))
    device = replay_mergetree_batch(docs)
    for doc, dev in zip(docs, device):
        replica = SharedString(doc.doc_id)
        ops = doc.ops
        if doc.binary_ops is not None:
            from fluidframework_tpu.ops.native_pack import decode_string_ops
            ops = decode_string_ops(
                doc.binary_ops, list(doc.binary_clients),
                prop_keys=doc.binary_prop_keys, values=doc.binary_values)
        for msg in ops:
            replica.process(msg, local=False)
        replica.advance(doc.final_seq, doc.final_msn)
        oracle = replica.summarize()
        assert dev.digest() == oracle.digest(), doc.doc_id


def test_chunk_packer_matches_per_doc_path(monkeypatch):
    """The per-chunk raw-pointer packer (base addr + d*row_bytes, shared
    scratch) fills bit-identical rows to the per-doc ndpointer path it
    replaced on the hot loop."""

    if load_library() is None:
        pytest.skip("liboppack unavailable")

    def build():
        docs = []
        for d in range(5):
            ops = synth_ops(300 + d, 40 + d, unicode_text=(d % 2 == 0))
            clients = Interner()
            blob = encode_string_ops(ops, clients)
            docs.append(MergeTreeDocInput(
                doc_id=f"doc{d}", ops=[], binary_ops=blob,
                binary_clients=list(clients.values),
                final_seq=len(ops), final_msn=0))
        return pack_mergetree_batch(docs)

    st_fast, op_fast, meta_fast = build()
    # pack_mergetree_batch re-imports chunk_packer per call, so patching
    # the module attribute reroutes the second build to the per-doc path.
    import fluidframework_tpu.ops.native_pack as npk
    monkeypatch.setattr(npk, "chunk_packer", lambda op: None)
    st_slow, op_slow, meta_slow = build()
    for name in op_fast._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(op_fast, name)),
            np.asarray(getattr(op_slow, name)), err_msg=name)
    assert meta_fast["arena"].finalize() == meta_slow["arena"].finalize()
