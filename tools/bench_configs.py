"""All five BASELINE.json configs measured: CPU oracle vs device path.

BASELINE.md's measurement table is produced by this harness (run on the
bench TPU; the committed numbers there cite the run).  Each config times

- the CPU oracle (per-op ``process`` replay through the DDS, the pinned 1×
  denominator) on a doc sample, and
- the device path END-TO-END (pack → fold → download → canonical summary
  extraction) over the full doc population, chunked like production,

and asserts byte-identical summaries on sampled docs.  Workloads are
seeded and deterministic; sizes via BENCHCFG_* env vars.

Configs (BASELINE.json):
  1 sharedstring  — merge-tree insert/remove/annotate replay (bench.py's
                    pinned workload, reused here)
  2 map           — SharedMap LWW set/delete/clear replay
  3 intervals     — SharedString + IntervalCollection annotate workload
  4 matrix        — SharedMatrix row/col insert/remove + cell sets
  5 tree          — SharedTree edit replay (insert/set/remove/move)

Prints one human table to stderr and ONE JSON line to stdout:
    {"metric": "baseline_configs", "configs": {...per-config rows...}}
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from fluidframework_tpu.dds import (  # noqa: E402
    SharedMap,
    SharedMatrix,
    SharedString,
)
from fluidframework_tpu.dds.tree import ROOT_ID, SharedTree  # noqa: E402
from fluidframework_tpu.ops.map_kernel import (  # noqa: E402
    MapDocInput,
    replay_map_batch,
)
from fluidframework_tpu.ops.matrix_kernel import (  # noqa: E402
    MatrixDocInput,
    replay_matrix_batch,
)
from fluidframework_tpu.ops.mergetree_kernel import (  # noqa: E402
    MergeTreeDocInput,
)
from fluidframework_tpu.ops.tree_kernel import (  # noqa: E402
    TreeDocInput,
    replay_tree_batch,
)
from fluidframework_tpu.protocol.messages import (  # noqa: E402
    MessageType,
    SequencedMessage,
)
from fluidframework_tpu.testing.mocks import (  # noqa: E402
    MockContainerRuntimeFactory,
    channel_log,
)

CHUNK = int(os.environ.get("BENCHCFG_CHUNK", "1024"))
CPU_SAMPLE = int(os.environ.get("BENCHCFG_CPU_SAMPLE", "64"))
SANITY_SAMPLE = 3


def _msg(seq: int, client: str, contents: dict) -> SequencedMessage:
    return SequencedMessage(
        seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
        min_seq=0, type=MessageType.OP, contents=contents,
    )


# -- workload generators (seeded, deterministic) ------------------------------


def gen_string_doc(idx: int, n_ops: int) -> MergeTreeDocInput:
    """Config #1: bench.py's pinned workload (binary-stream ingestion)."""
    import bench

    return bench.synth_doc(idx, n_ops)


def gen_map_doc(idx: int, n_ops: int) -> MapDocInput:
    """Config #2: LWW key traffic over a zipf-ish key population, 3 clients,
    92% set / 6% delete / 2% clear."""
    rng = random.Random(idx * 6271 + 5)
    n_keys = 24
    ops = []
    for i in range(n_ops):
        seq = i + 1
        client = f"client{i % 3}"
        r = rng.random()
        key = f"k{int(rng.random() ** 2 * n_keys)}"
        if r < 0.92:
            contents = {"kind": "set", "key": key,
                        "value": rng.randint(0, 999)}
        elif r < 0.98:
            contents = {"kind": "delete", "key": key}
        else:
            contents = {"kind": "clear"}
        ops.append(_msg(seq, client, contents))
    return MapDocInput(doc_id=f"map{idx}", ops=ops)


ALPHABET = "abcdefghijklmnopqrstuvwxyz "


def gen_interval_doc(idx: int, n_ops: int) -> MergeTreeDocInput:
    """Config #3: text traffic carrying a live interval population —
    adds/changes/deletes against sliding local references (message-list
    ingestion; interval ops never ride the binary stream)."""
    rng = random.Random(idx * 9973 + 29)
    ops, length = [], 0
    live: list = []
    for i in range(n_ops):
        seq = i + 1
        client = f"client{i % 3}"
        r = rng.random()
        if r < 0.5 or length < 8:
            pos = rng.randint(0, length)
            text = "".join(
                rng.choice(ALPHABET) for _ in range(rng.randint(1, 8))
            )
            contents = {"kind": "insert", "pos": pos, "text": text}
            length += len(text)
        elif r < 0.7:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(1, 8))
            contents = {"kind": "remove", "start": start, "end": end}
            length -= end - start
        elif r < 0.85 or not live:
            iid = f"iv{idx}-{seq}"
            start = rng.randint(0, length - 2)
            end = min(length - 1, start + rng.randint(1, 12))
            contents = {"kind": "intervalAdd", "label": "default",
                        "id": iid, "start": start, "end": end,
                        "props": {"c": rng.randint(0, 5)}}
            live.append(iid)
        elif r < 0.95:
            iid = rng.choice(live)
            start = rng.randint(0, length - 2)
            contents = {"kind": "intervalChange", "label": "default",
                        "id": iid, "start": start,
                        "end": min(length - 1, start + rng.randint(1, 12))}
        else:
            iid = live.pop(rng.randrange(len(live)))
            contents = {"kind": "intervalDelete", "label": "default",
                        "id": iid}
        ops.append(_msg(seq, client, contents))
    return MergeTreeDocInput(doc_id=f"iv{idx}", ops=ops,
                             final_seq=n_ops, final_msn=0)


def gen_matrix_doc(idx: int, n_ops: int) -> MatrixDocInput:
    """Config #4: row/col growth + removals + cell sets on the live grid."""
    rng = random.Random(idx * 3557 + 11)
    ops, rows, cols = [], 0, 0
    for i in range(n_ops):
        seq = i + 1
        client = f"client{i % 3}"
        r = rng.random()
        if r < 0.18 or rows == 0:
            count = rng.randint(1, 3)
            contents = {"kind": "insertRows",
                        "pos": rng.randint(0, rows), "count": count}
            rows += count
        elif r < 0.36 or cols == 0:
            count = rng.randint(1, 3)
            contents = {"kind": "insertCols",
                        "pos": rng.randint(0, cols), "count": count}
            cols += count
        elif r < 0.42 and rows > 2:
            start = rng.randint(0, rows - 2)
            end = min(rows, start + rng.randint(1, 2))
            contents = {"kind": "removeRows", "start": start, "end": end}
            rows -= end - start
        elif r < 0.48 and cols > 2:
            start = rng.randint(0, cols - 2)
            end = min(cols, start + rng.randint(1, 2))
            contents = {"kind": "removeCols", "start": start, "end": end}
            cols -= end - start
        else:
            contents = {"kind": "setCell", "row": rng.randint(0, rows - 1),
                        "col": rng.randint(0, cols - 1),
                        "value": rng.randint(0, 999)}
        ops.append(_msg(seq, client, contents))
    return MatrixDocInput(doc_id=f"mx{idx}", ops=ops,
                          final_seq=n_ops, final_msn=0)


def gen_tree_doc(idx: int, n_edits: int) -> TreeDocInput:
    """Config #5: drive a SharedTree client through the mock sequencer
    (tree changesets carry anchors/ids a raw generator can't fabricate)."""
    rng = random.Random(idx * 4099 + 17)
    factory = MockContainerRuntimeFactory()
    t = factory.create_client("client0").attach(SharedTree("tree"))
    nodes: list = []
    for _ in range(n_edits):
        roll = rng.random()
        if roll < 0.45 or len(nodes) < 3:
            field = rng.choice(["a", "b"])
            kids = t.children(ROOT_ID, field)
            [nid] = t.insert(ROOT_ID, field, rng.randint(0, len(kids)),
                             [t.build("n", value=rng.randint(0, 99))])
            nodes.append(nid)
        elif roll < 0.75:
            t.set_value(rng.choice(nodes), rng.randint(0, 999))
        elif roll < 0.88:
            nid = nodes.pop(rng.randrange(len(nodes)))
            t.remove(nid)
        else:
            nid = rng.choice(nodes)
            field = rng.choice(["a", "b"])
            kids = [k for k in t.children(ROOT_ID, field) if k != nid]
            t.move([nid], ROOT_ID, field, rng.randint(0, len(kids)))
        factory.process_all_messages()
    return TreeDocInput(
        doc_id=f"tree{idx}", ops=channel_log(factory, "tree"),
        final_seq=factory.sequencer.seq, final_msn=factory.sequencer.min_seq,
    )


# -- oracle replays -----------------------------------------------------------


def oracle_string(doc: MergeTreeDocInput):
    replica = SharedString(doc.doc_id)
    for msg in doc.ops:
        replica.process(msg, local=False)
    replica.advance(doc.final_seq, doc.final_msn)
    return replica.summarize()


def oracle_map(doc: MapDocInput):
    replica = SharedMap(doc.doc_id)
    for msg in doc.ops:
        replica.process(msg, local=False)
    return replica.summarize()


def oracle_matrix(doc: MatrixDocInput):
    replica = SharedMatrix(doc.doc_id)
    for msg in doc.ops:
        replica.process(msg, local=False)
    replica.advance(doc.final_seq, doc.final_msn)
    return replica.summarize()


def oracle_tree(doc: TreeDocInput):
    from fluidframework_tpu.ops.tree_kernel import oracle_fallback_summary

    return oracle_fallback_summary(doc)


# -- the measurement loop -----------------------------------------------------


def _pipelined_string(docs, stats=None, stage=None):
    """Config #1/#3 device path = the PRODUCT pipeline (the same chunked
    single-device-thread fold the catch-up service runs)."""
    from fluidframework_tpu.ops.pipeline import pipelined_mergetree_replay

    return pipelined_mergetree_replay(docs, chunk_docs=CHUNK, stats=stats,
                                      stage=stage)


def run_config(name, docs, n_ops, oracle_fn, device_batch_fn,
               self_chunked=False):
    total_ops = sum(n_ops(d) for d in docs)
    sample = docs[:CPU_SAMPLE]
    t0 = time.time()
    oracle_digests = [oracle_fn(d).digest() for d in sample]
    cpu_t = time.time() - t0
    cpu_rate = sum(n_ops(d) for d in sample) / cpu_t

    # Device end-to-end (chunked like production).  Warm the compile cache
    # on a FULL first chunk — the (S, T) buckets derive from batch maxima,
    # so a tiny warm batch would compile a different shape and leave the
    # real compilation inside the timed loop.  ``self_chunked`` fns (the
    # product's pipelined replay) receive the whole population in one
    # call and chunk/overlap internally.
    device_batch_fn(docs[:CHUNK])
    stats: dict = {}
    stage: dict = {}
    t0 = time.time()
    if self_chunked:
        # The product pipeline carries the honest stage split
        # (device_wait vs download) + the d2h/h2d byte counters.
        summaries = list(device_batch_fn(docs, stats=stats, stage=stage))
    else:
        summaries = []
        for i in range(0, len(docs), CHUNK):
            summaries.extend(device_batch_fn(docs[i:i + CHUNK], stats=stats))
    dev_t = time.time() - t0
    dev_rate = total_ops / dev_t

    for d in range(0, len(sample), max(1, len(sample) // SANITY_SAMPLE)):
        assert summaries[d].digest() == oracle_digests[d], (
            f"{name}: doc {d} device summary != oracle"
        )
    row = {
        "n_docs": len(docs),
        "total_ops": total_ops,
        "cpu_ops_per_sec": round(cpu_rate, 1),
        "device_ops_per_sec": round(dev_rate, 1),
        "vs_baseline": round(dev_rate / cpu_rate, 2),
        "device_sec": round(dev_t, 3),
        "fallback_docs": stats.get("fallback_docs", 0),
        "device_docs": stats.get("device_docs", 0),
        # Null-stable on non-pipeline configs (no stage instrumentation).
        "stages_busy_sec": ({
            k: round(v, 3) for k, v in sorted(stage.items())
            if k not in ("d2h_bytes", "h2d_bytes")
        } if stage else None),
        "d2h_bytes": (int(stage.get("d2h_bytes", 0)) if stage else None),
        "h2d_bytes": (int(stage.get("h2d_bytes", 0)) if stage else None),
    }
    print(
        f"{name:12s} docs={len(docs):5d} ops={total_ops:7d} "
        f"cpu={cpu_rate:10,.0f}/s device={dev_rate:10,.0f}/s "
        f"ratio={row['vs_baseline']:6.2f}x "
        f"fallbacks={row['fallback_docs']}/{len(docs)}",
        file=sys.stderr,
    )
    return row


def main() -> None:
    """Environment-hardened entry: bench.run_hardened is the ONE shared
    harness (probe skip-line, deadline watchdog, env-vs-bug-vs-correctness
    classification) — no second copy to drift out of sync."""
    import bench

    bench.run_hardened(
        "baseline_configs", _run_configs,
        float(os.environ.get("BENCHCFG_DEADLINE", "3000")),
        skip_base={"configs": None},
    )


def _run_configs(probe: dict) -> dict:
    sizes = {
        "sharedstring": (int(os.environ.get("BENCHCFG_STRING_DOCS", "4096")),
                         96),
        "map": (int(os.environ.get("BENCHCFG_MAP_DOCS", "4096")), 96),
        "intervals": (int(os.environ.get("BENCHCFG_IV_DOCS", "2048")), 96),
        "matrix": (int(os.environ.get("BENCHCFG_MATRIX_DOCS", "1024")), 64),
        "tree": (int(os.environ.get("BENCHCFG_TREE_DOCS", "256")), 48),
    }
    print(f"backend={jax.default_backend()}", file=sys.stderr)
    results = {}

    n, k = sizes["sharedstring"]
    t0 = time.time()
    docs = [gen_string_doc(i, k) for i in range(n)]
    print(f"gen sharedstring {time.time()-t0:.1f}s", file=sys.stderr)
    results["sharedstring"] = run_config(
        "sharedstring", docs, lambda d: k,
        oracle_string_binary, _pipelined_string, self_chunked=True,
    )

    n, k = sizes["map"]
    t0 = time.time()
    docs = [gen_map_doc(i, k) for i in range(n)]
    print(f"gen map {time.time()-t0:.1f}s", file=sys.stderr)
    results["map"] = run_config(
        "map", docs, lambda d: len(d.ops), oracle_map, replay_map_batch,
    )

    n, k = sizes["intervals"]
    t0 = time.time()
    docs = [gen_interval_doc(i, k) for i in range(n)]
    print(f"gen intervals {time.time()-t0:.1f}s", file=sys.stderr)
    results["intervals"] = run_config(
        "intervals", docs, lambda d: len(d.ops),
        oracle_string, _pipelined_string, self_chunked=True,
    )

    n, k = sizes["matrix"]
    t0 = time.time()
    docs = [gen_matrix_doc(i, k) for i in range(n)]
    print(f"gen matrix {time.time()-t0:.1f}s", file=sys.stderr)
    results["matrix"] = run_config(
        "matrix", docs, lambda d: len(d.ops),
        oracle_matrix, replay_matrix_batch,
    )

    n, k = sizes["tree"]
    t0 = time.time()
    docs = [gen_tree_doc(i, k) for i in range(n)]
    print(f"gen tree {time.time()-t0:.1f}s", file=sys.stderr)
    results["tree"] = run_config(
        "tree", docs, lambda d: len(d.ops), oracle_tree, replay_tree_batch,
    )

    return {
        "metric": "baseline_configs",
        "backend": probe.get("platform", jax.default_backend()),
        "device_kind": probe.get("device_kind", "?"),
        "configs": results,
    }


def oracle_string_binary(doc: MergeTreeDocInput):
    """Oracle for binary-stream docs (config #1 reuses bench.synth_doc)."""
    import bench

    return bench.oracle_replay(doc).summarize()


if __name__ == "__main__":
    main()
