"""One-command TPU-window preflight gate (run it BEFORE the chain).

A tunnel window is minutes long; the classes of failure that historically
burned them are all detectable on CPU first:

  * the round-5 Mosaic compile error — a BlockSpec/grid shape violating
    the (8, 128) rule that interpret mode silently accepts;
  * the round-13 int16 overflow — a narrow plane built without its bound
    guard;
  * kernel/oracle divergence — a fold change that was never re-run
    against the reference before the window;
  * artifact-schema drift — bench.py's roofline block renamed or dropped
    a key the window consumers read.

Four gates, all CPU-runnable, each reported in one JSON summary line on
stdout; exit 0 iff every gate passed.  ``tools/tpu_window.sh`` runs this
as the FIRST command of a healthy window and keeps probing instead of
burning the window when it fails.

  1. kernel-lint  — the fluidshape family (FL-KERN-*) over the package
     must be clean with ZERO suppressions (static Mosaic compliance,
     narrow-dtype bounds, bucket routing, pad masking, registry drift).
  2. mergetree-parity — interpret-mode Pallas fold vs the jitted scan
     reference on a small synth batch, field-exact on live slots.
  3. tree-parity  — device tree fold vs the CPU oracle on a minimal
     sequenced log, digest-exact.
  4. bench-schema — the roofline dict carries the keys the artifacts
     commit, and ``steady_fold_pct_of_bound`` is still derivable from
     it (and still spelled that way inside bench.py).

NOTE (SEMANTICS.md): gate 1 is a static approximation and gates 2-3 run
in interpret mode — passing preflight does NOT prove the kernel Mosaic-
compiles on a real chip; that remains the pallas canary's job inside the
window.  Preflight exists so the window is never spent discovering what
CPU could have told us.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _gate(fn):
    """Run one gate; never raise — a preflight that crashes is a FAILED
    preflight with the traceback as detail, not a wedged window."""
    import traceback

    try:
        detail = fn()
        return {"ok": True, "detail": detail}
    except Exception:
        return {"ok": False, "detail": traceback.format_exc(limit=4)}


def gate_kernel_lint():
    """fluidshape (FL-KERN-*) over the whole package, zero suppressions."""
    from tools.fluidlint.cli import rule_family
    from tools.fluidlint.core import all_rules, analyze

    rules = {name: rule for name, rule in all_rules().items()
             if rule_family(rule) == "kernel"}
    assert len(rules) >= 5, sorted(rules)
    findings = analyze(ROOT, rules=rules)
    assert not findings, [f.render() for f in findings]
    return f"{len(rules)} FL-KERN rules, 0 findings, 0 suppressions"


def gate_mergetree_parity():
    """Interpret-mode Pallas fold == jitted scan reference, field-exact
    on live slots (dead-slot garbage above ``n`` may differ)."""
    import jax
    import numpy as np

    import bench
    from fluidframework_tpu.ops.mergetree_kernel import (
        pack_mergetree_batch,
        replay_vmapped,
    )
    from fluidframework_tpu.ops.pallas_fold import replay_vmapped_pallas

    docs = [bench.synth_doc(i, 16) for i in range(5)]
    state, ops, _meta = pack_mergetree_batch(docs)
    final_scan = jax.jit(replay_vmapped)(state, ops)
    final_pallas = replay_vmapped_pallas(state, ops, interpret=True)
    n = np.asarray(final_scan.n)
    for field in final_scan._fields:
        av = np.asarray(getattr(final_scan, field))
        bv = np.asarray(getattr(final_pallas, field))
        assert av.shape == bv.shape, field
        if field in ("n", "overflow"):
            assert np.array_equal(av, bv), field
            continue
        for d in range(len(docs)):
            nd = int(n[d])
            assert np.array_equal(av[d, :nd], bv[d, :nd]), \
                f"{field} doc {d}"
    return f"{len(docs)} docs, scan == pallas(interpret=True)"


def gate_tree_parity():
    """Device tree fold == CPU oracle on a minimal sequenced log."""
    from fluidframework_tpu.ops.tree_kernel import (
        TreeDocInput,
        oracle_fallback_summary,
        replay_tree_batch,
    )
    from fluidframework_tpu.protocol.messages import (
        MessageType,
        SequencedMessage,
    )

    def op(seq, edits):
        return SequencedMessage(
            seq=seq, client_id="c0", client_seq=seq, ref_seq=seq - 1,
            min_seq=0, type=MessageType.OP, contents={"edits": edits},
        )

    log = [
        op(1, [{"kind": "insert", "parent": "", "field": "a",
                "anchor": None,
                "content": [{"id": "A", "type": "n", "value": 1}]}]),
        op(2, [{"kind": "insert", "parent": "", "field": "a",
                "anchor": None,
                "content": [{"id": "B", "type": "n", "value": 2}]}]),
        op(3, [{"kind": "move", "ids": ["B"], "parent": "A",
                "field": "kids", "anchor": None,
                "prev": [["B", "", "a", None]]}]),
        op(4, [{"kind": "remove", "ids": ["A"]}]),
    ]
    doc = TreeDocInput(doc_id="preflight", ops=log, final_seq=4,
                       final_msn=0)
    (device,) = replay_tree_batch([doc])
    assert device.digest() == oracle_fallback_summary(doc).digest()
    return "1 doc, device digest == oracle digest"


def gate_bench_schema():
    """The roofline block bench.py commits to window artifacts still has
    the schema the consumers read, and the derived key is still spelled
    ``steady_fold_pct_of_bound`` at the producer."""
    import bench

    roof = bench.roofline(96, 4, "TPU_v4")
    required = {"S", "props_plane_K", "bytes_per_op_optimistic",
                "hbm_GBps", "device_kind", "bound_ops_per_sec"}
    missing = required - set(roof)
    assert not missing, f"roofline schema lost keys: {sorted(missing)}"
    assert roof["bound_ops_per_sec"] > 0, roof
    # The dry-run derivation the bench performs in-window:
    roof["steady_fold_pct_of_bound"] = round(
        100.0 * 1.0 / roof["bound_ops_per_sec"], 2)
    assert roof["steady_fold_pct_of_bound"] >= 0
    src = open(os.path.join(ROOT, "bench.py"), encoding="utf-8").read()
    assert "steady_fold_pct_of_bound" in src, \
        "bench.py no longer produces steady_fold_pct_of_bound"
    json.dumps(roof)  # artifact-serializable, schema-stable
    return "roofline schema ok, steady_fold_pct_of_bound derivable"


def main() -> int:
    gates = {
        "kernel_lint": _gate(gate_kernel_lint),
        "mergetree_parity": _gate(gate_mergetree_parity),
        "tree_parity": _gate(gate_tree_parity),
        "bench_schema": _gate(gate_bench_schema),
    }
    ok = all(g["ok"] for g in gates.values())
    print(json.dumps({"metric": "tpu_preflight", "preflight_ok": ok,
                      "gates": gates}))
    for name, g in gates.items():
        if not g["ok"]:
            print(f"preflight gate {name} FAILED:\n{g['detail']}",
                  file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
