#!/bin/bash
# Round-5 TPU window catcher: probe the axon tunnel on a loop; in the FIRST
# healthy window run the full measurement chain (bench.py on the
# single-device-thread pipeline, a legacy-pipeline A/B, the five-config
# table), each timeboxed.  Artifacts whose run exited 0 with a parseable
# JSON line are committed LOCALLY (no remote exists in this environment —
# the driver collects the repo).  Status: window_artifacts/status.log
cd "$(dirname "$0")/.." || exit 1
mkdir -p window_artifacts
# Singleton via pidfile (pkill -f patterns match unrelated shells whose
# command lines merely mention this path — kill by pid, never by name).
if [ -f window_artifacts/catcher.pid ] \
    && kill -0 "$(cat window_artifacts/catcher.pid)" 2>/dev/null; then
  exit 0
fi
echo $$ > window_artifacts/catcher.pid
log() { echo "$(date -u +%H:%M:%S) $*" >> window_artifacts/status.log; }
log "catcher started pid $$"
run_one() {  # run_one <name> <cmd...> ; returns 0 on accepted artifact
  local name="$1"; shift
  timeout 580 env "$@" > "window_artifacts/$name.json" 2> "window_artifacts/$name.err"
  local rc=$?
  log "$name rc=$rc $(head -c 120 "window_artifacts/$name.json")"
  if [ "$rc" -eq 0 ] && python -c "import json,sys; json.load(open('window_artifacts/$name.json'))" 2>/dev/null; then
    cp "window_artifacts/$name.json" "BENCH_tpu_window_$name.json" && KEEP+=("BENCH_tpu_window_$name.json")
    return 0
  fi
  log "$name artifact rejected (rc=$rc or unparseable) — not committed"
  return 1
}
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    log "HEALTHY — starting measurement chain"
    pkill -f test_fuzz_nightly 2>/dev/null; pkill -f "pytest tests/" 2>/dev/null; sleep 2
    # Preflight FIRST (ISSUE 20): kernel lint + interpret-mode parity +
    # bench schema, all CPU-answerable — never spend the window
    # discovering a failure CPU could have reported.  On failure keep
    # probing: the next window may follow a fix.
    if ! timeout 580 python tools/tpu_preflight.py \
        > window_artifacts/preflight.json 2> window_artifacts/preflight.err; then
      log "preflight FAILED — window not spent ($(head -c 160 window_artifacts/preflight.err))"
      sleep 150
      continue
    fi
    log "preflight ok $(head -c 120 window_artifacts/preflight.json)"
    KEEP=()
    MAIN_OK=0
    # Canary first (smallest, highest-information: the Mosaic compile),
    # once per session; the benches then skip their built-in canary so a
    # slow Mosaic compile can't eat the main runs' timeboxes.
    if [ ! -f BENCH_tpu_window_pallas.json ]; then
      run_one pallas python tools/pallas_probe.py
    fi
    run_one sdt FF_NO_PALLAS_CANARY=1 python bench.py && MAIN_OK=1
    run_one legacy FF_NO_PALLAS_CANARY=1 BENCH_E2E_PIPELINE=legacy python bench.py && MAIN_OK=1
    run_one configs FF_NO_PALLAS_CANARY=1 python tools/bench_configs.py && MAIN_OK=1
    # Streaming-fold counters under the real backend (ISSUE 16): the
    # catchup-storm gate with the sequencer-attached streaming fold on
    # vs off — steady fold rate, lag, lanes, truncation bytes (loadgen
    # --stream prints the JSON document to stdout).
    run_one streamfold FF_NO_PALLAS_CANARY=1 python -m tools.loadgen --stream --clients 1200 --docs 8 --shards 4 --seed 16 && MAIN_OK=1
    if [ "${#KEEP[@]}" -gt 0 ]; then
      log "committing ${#KEEP[@]} artifact(s): ${KEEP[*]}"
      git add -- "${KEEP[@]}" && \
        git commit -q -m "TPU window measurement chain artifacts (${KEEP[*]})" -- "${KEEP[@]}" \
        && log "commit ok" || log "commit FAILED"
    fi
    if [ "$MAIN_OK" -ne 1 ]; then
      # A canary alone does not satisfy the window — the catcher exists
      # for the north-star e2e; keep probing for a healthier window.
      log "no main artifact yet — will keep probing"
      sleep 150
      continue
    fi
    touch window_artifacts/CHAIN_DONE
    log "chain complete"
    exit 0
  else
    log "WEDGED"
  fi
  sleep 150
done
