#!/bin/bash
# Round-5 TPU window catcher: probe the axon tunnel on a loop; in the FIRST
# healthy window run the full measurement chain (bench.py on the
# single-device-thread pipeline, a legacy-pipeline A/B, the five-config
# table), each timeboxed, artifacts to window_artifacts/.  The operator
# (or the next session) commits what lands.  Status: window_artifacts/status.log
cd "$(dirname "$0")/.." || exit 1
mkdir -p window_artifacts
log() { echo "$(date -u +%H:%M:%S) $*" >> window_artifacts/status.log; }
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    log "HEALTHY — starting measurement chain"
    pkill -f test_fuzz_nightly 2>/dev/null; sleep 2
    timeout 580 python bench.py > window_artifacts/bench_sdt.json 2> window_artifacts/bench_sdt.err
    log "bench sdt rc=$? $(head -c 120 window_artifacts/bench_sdt.json)"
    BENCH_E2E_PIPELINE=legacy timeout 580 python bench.py > window_artifacts/bench_legacy.json 2> window_artifacts/bench_legacy.err
    log "bench legacy rc=$?"
    timeout 580 python tools/bench_configs.py > window_artifacts/bench_configs.json 2> window_artifacts/bench_configs.err
    log "configs rc=$?"
    touch window_artifacts/CHAIN_DONE
    log "chain complete"
    exit 0
  else
    log "WEDGED"
  fi
  sleep 150
done
