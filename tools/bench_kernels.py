"""Second-kernel-family benches (ISSUE 14): 10k-doc SharedTree rebase +
interval stabbing as first-class workloads on the generic pipeline.

Through round 13 every bench measured only SharedString catch-up; this
harness is the load-bearing proof that the cache/pipeline abstractions
are not merge-tree-shaped:

- **tree_rebase** — 10k-doc SharedTree catch-up through
  ``pipelined_tree_replay`` (deep-move chains, wide-container fan-out,
  plus the fallback shapes: revive, multi-id move, MAX_DEPTH overflow),
  cold → warm-exact → warm-grown, with the full r13 stage schema
  (``pack/upload/dispatch/device_wait/download/extract``,
  ``h2d_bytes``/``d2h_bytes``), per-reason fallback accounting, all four
  cache tiers' counters, and a CatchupService cold/warm pass whose warm
  serve must be pure tier-1 (``cache_hit_rate`` 1.0, h2d == d2h == 0);
- **interval_stabbing** — 10k string documents whose interval
  populations attach references across segments that later removes
  force through the lazy slide cascade (``ops/interval_replay.py``'s
  hot path: bounded-visibility stabs + ``anchor_final`` cascades),
  folded cold/warm through the SAME pipeline the string family serves.

Byte-identity is asserted in-run: caches-on == caches-off ==
``replay_tree_batch`` across the WHOLE population, re-asserted after a
forced epoch invalidation, and against the ``dds/`` per-op oracles on a
deterministic sample (``BENCHK_ORACLE_EVERY``; 1 = every doc).

Prints ONE JSON line (``bench.run_hardened`` — probe skip-line, deadline
watchdog, correctness-vs-environment classification):

    JAX_PLATFORMS=cpu python tools/bench_kernels.py \
        > BENCH_kernels_cpu_r14.json
"""

from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.ops.mergetree_kernel import (  # noqa: E402
    MergeTreeDocInput,
)
from fluidframework_tpu.ops.tree_kernel import (  # noqa: E402
    MAX_DEPTH,
    TreeDocInput,
)
from fluidframework_tpu.protocol.messages import (  # noqa: E402
    MessageType,
    SequencedMessage,
)

METRIC = "kernel_families"

TREE_DOCS = int(os.environ.get("BENCHK_TREE_DOCS", "10240"))
TREE_EDITS = int(os.environ.get("BENCHK_TREE_EDITS", "48"))
IV_DOCS = int(os.environ.get("BENCHK_IV_DOCS", "10240"))
IV_OPS = int(os.environ.get("BENCHK_IV_OPS", "96"))
#: oracle sampling stride (1 = byte-check EVERY doc against the dds
#: oracle; the cross-configuration parity below is always full-corpus)
ORACLE_EVERY = int(os.environ.get("BENCHK_ORACLE_EVERY", "4"))
CHUNK = int(os.environ.get("BENCHK_CHUNK", "1024"))
GROW_EVERY = int(os.environ.get("BENCHK_GROW_EVERY", "8"))
DEADLINE = float(os.environ.get("BENCHK_DEADLINE", "2700"))

ALPHABET = "abcdefghijklmnopqrstuvwxyz "

#: deterministic workload-shape assignment: the three fallback shapes
#: ride along at ~9% so the per-reason counters have real traffic, the
#: rest splits between the two device-path shapes.
def tree_shape(idx: int) -> str:
    r = idx % 32
    if r == 0:
        return "revive"
    if r == 1:
        return "multi_id_move"
    if r == 2:
        return "max_depth"
    return "deep-move" if idx % 2 == 0 else "wide-container"


def _msg(seq: int, min_seq: int, edits: list) -> SequencedMessage:
    return SequencedMessage(
        seq=seq, client_id=f"c{seq % 3}", client_seq=seq, ref_seq=seq - 1,
        min_seq=min_seq, type=MessageType.OP, contents={"edits": edits},
    )


def synth_tree_messages(idx: int, n_edits: int):
    """One document's deterministic SharedTree changeset stream.

    Shapes (see :func:`tree_shape`): ``deep-move`` builds a nested chain
    and keeps moving leaves (and chain nodes — including dropped-cycle
    moves) through its containers, the ancestor-walk-heavy rebase case;
    ``wide-container`` fans leaves out under two root fields with
    anchored inserts/removes/sets/moves; the fallback shapes inject one
    revive, one multi-id move, or a > MAX_DEPTH chain + move (device
    overflow) into otherwise-normal traffic.  ``min_seq`` advances
    periodically so purge windows and purge-gated edits execute."""
    rng = random.Random(idx * 48611 + 7)
    shape = tree_shape(idx)
    msgs, seq, min_seq = [], 0, 0
    counter = [0]

    def fresh() -> str:
        counter[0] += 1
        return f"t{idx}-n{counter[0]}"

    def emit(*edits):
        nonlocal seq, min_seq
        seq += 1
        if seq > 24 and seq % 10 == 0:
            min_seq = seq - 20
        msgs.append(_msg(seq, min_seq, list(edits)))

    def leaf(value: int) -> dict:
        return {"id": fresh(), "type": "n", "value": value}

    def ins(parent: str, field: str, spec: dict, anchor=None) -> dict:
        return {"kind": "insert", "parent": parent, "field": field,
                "anchor": anchor, "content": [spec]}

    live: list = []
    chain: list = []
    if shape in ("deep-move", "max_depth"):
        depth = (MAX_DEPTH + 6) if shape == "max_depth" \
            else rng.randint(8, 20)
        spec = leaf(0)
        chain.append(spec["id"])
        root_spec = spec
        for _ in range(depth - 1):
            child = leaf(0)
            spec["fields"] = {"k": [child]}
            spec = child
            chain.append(spec["id"])
        emit(ins("", "a", root_spec))
        if shape == "max_depth":
            # Guarantee the overflow: a move whose destination sits
            # below MAX_DEPTH ancestors makes the device's cycle walk
            # overflow deterministically (the doc's fallback REASON).
            probe = leaf(1)
            live.append((probe["id"], probe["value"]))
            emit(ins("", "b", probe))
            emit({"kind": "move", "ids": [probe["id"]],
                  "parent": chain[-1], "field": "k", "anchor": None})
    removed: list = []
    for i in range(n_edits - len(msgs)):
        roll = rng.random()
        if shape == "revive" and i == n_edits // 2 and removed:
            nid, value = removed[-1]
            emit({"kind": "revive", "ids": [nid], "parent": "",
                  "field": "a", "anchor": None,
                  "content": [{"id": nid, "type": "n", "value": value}]})
            continue
        if shape == "multi_id_move" and i == n_edits // 2 \
                and len(live) >= 2:
            emit({"kind": "move", "ids": [live[0][0], live[1][0]],
                  "parent": "", "field": "b", "anchor": None})
            continue
        if shape in ("deep-move", "max_depth") and roll < 0.35 and chain:
            target_parent = rng.choice(chain)
            if roll < 0.12 and live:
                # move a leaf deep into the chain (the ancestor-walk
                # stab; on the max_depth shape this overflows)
                emit({"kind": "move", "ids": [rng.choice(live)[0]],
                      "parent": target_parent, "field": "k",
                      "anchor": None})
            elif roll < 0.2 and len(chain) > 4:
                # chain node into its own descendant: the CYCLE case —
                # dropped identically by oracle and device
                hi = rng.randrange(2, len(chain) - 1)
                emit({"kind": "move", "ids": [chain[hi - 1]],
                      "parent": chain[hi], "field": "k", "anchor": None})
            else:
                spec = leaf(rng.randint(0, 99))
                live.append((spec["id"], spec["value"]))
                emit(ins(target_parent, "k", spec))
        elif roll < 0.45 or len(live) < 3:
            spec = leaf(rng.randint(0, 99))
            anchor = (rng.choice(live)[0]
                      if live and rng.random() < 0.5 else None)
            live.append((spec["id"], spec["value"]))
            emit(ins("", rng.choice(["a", "b"]), spec, anchor=anchor))
        elif roll < 0.65:
            nid, _v = rng.choice(live)
            emit({"kind": "set", "id": nid,
                  "value": rng.randint(0, 999)})
        elif roll < 0.8:
            k = rng.randrange(len(live))
            nid, value = live.pop(k)
            removed.append((nid, value))
            emit({"kind": "remove", "ids": [nid]})
        else:
            nid, _v = rng.choice(live)
            anchor = (rng.choice(live)[0]
                      if rng.random() < 0.5 else None)
            if anchor == nid:
                anchor = None
            emit({"kind": "move", "ids": [nid], "parent": "",
                  "field": rng.choice(["a", "b"]), "anchor": anchor})
    return msgs


def tree_doc(idx: int, msgs, n_msgs: int) -> TreeDocInput:
    """The catch-up work item over the stream's first ``n_msgs``
    messages — a fixed token, so grown windows extend under the tier
    identity contract."""
    window = msgs[:n_msgs]
    return TreeDocInput(
        doc_id=f"tdoc{idx}", ops=window, final_seq=window[-1].seq,
        final_msn=window[-1].min_seq,
        cache_token=("bench-epoch", f"tdoc{idx}", 0, ""),
    )


def synth_interval_doc(idx: int, n_ops: int,
                       n_msgs=None) -> MergeTreeDocInput:
    """A string document with a DENSE interval population over segments
    that later removes force through the slide cascade: phase 1 builds
    text, phase 2 attaches ~n/4 intervals across it, phase 3 removes
    spans (every ref on a removed segment must slide — repeatedly, when
    the landing segment is itself removed later), phase 4 keeps
    churning adds/changes/deletes.  The stabbing workload for
    ``ops/interval_replay.py``."""
    rng = random.Random(idx * 7103 + 3)
    ops, length = [], 0
    live: list = []
    for i in range(n_ops):
        seq = i + 1
        client = f"client{i % 3}"
        phase = i * 4 // n_ops
        r = rng.random()
        if phase == 0 or length < 16:
            pos = rng.randint(0, length)
            text = "".join(
                rng.choice(ALPHABET) for _ in range(rng.randint(2, 8)))
            contents = {"kind": "insert", "pos": pos, "text": text}
            length += len(text)
        elif phase == 1 or (phase == 3 and (r < 0.4 or not live)):
            iid = f"iv{idx}-{seq}"
            start = rng.randint(0, length - 2)
            contents = {"kind": "intervalAdd", "label": "default",
                        "id": iid, "start": start,
                        "end": min(length - 1, start + rng.randint(1, 12)),
                        "props": {"c": rng.randint(0, 5)}}
            live.append(iid)
        elif phase == 2 and r < 0.7:
            start = rng.randint(0, length - 2)
            end = min(length, start + rng.randint(2, 10))
            contents = {"kind": "remove", "start": start, "end": end}
            length -= end - start
        elif r < 0.7:
            iid = rng.choice(live)
            start = rng.randint(0, max(0, length - 2))
            contents = {"kind": "intervalChange", "label": "default",
                        "id": iid, "start": start,
                        "end": min(length - 1,
                                   start + rng.randint(1, 12))}
        else:
            iid = live.pop(rng.randrange(len(live)))
            contents = {"kind": "intervalDelete", "label": "default",
                        "id": iid}
        ops.append(SequencedMessage(
            seq=seq, client_id=client, client_seq=seq, ref_seq=seq - 1,
            min_seq=0, type=MessageType.OP, contents=contents,
        ))
    window = ops[:n_msgs] if n_msgs is not None else ops
    return MergeTreeDocInput(
        doc_id=f"ivdoc{idx}", ops=window, final_seq=window[-1].seq,
        final_msn=0,
        cache_token=("bench-epoch", f"ivdoc{idx}", 0, ""),
    )


# ---------------------------------------------------------------------------
# The measurement passes
# ---------------------------------------------------------------------------


def _stage_row(stage: dict) -> dict:
    return {
        "stages_busy_sec": {
            k: round(v, 3) for k, v in sorted(stage.items())
            if k not in ("d2h_bytes", "h2d_bytes")
        },
        "h2d_bytes": int(stage.get("h2d_bytes", 0)),
        "d2h_bytes": int(stage.get("d2h_bytes", 0)),
    }


def _one_pass(replay, docs, total_ops, caches) -> tuple:
    stage = {"pack": 0.0, "upload": 0.0, "dispatch": 0.0,
             "device_wait": 0.0, "download": 0.0, "extract": 0.0,
             "d2h_bytes": 0, "h2d_bytes": 0}
    stats: dict = {}
    t0 = time.time()
    summaries = replay(docs, chunk_docs=CHUNK, stage=stage, stats=stats,
                       **caches)
    wall = time.time() - t0
    row = {
        "ops_per_sec": round(total_ops / wall, 1),
        "wall_sec": round(wall, 3),
        **_stage_row(stage),
        "stats": dict(sorted(stats.items())),
    }
    return [s.digest() for s in summaries], row


def run_tree_rebase() -> dict:
    """Cold → warm-exact → warm-grown tree rebase at 10k docs, full
    parity matrix, per-reason fallback accounting, and the service-tier
    warm catch-up gate."""
    from fluidframework_tpu.ops.tree_kernel import (
        oracle_fallback_summary,
        replay_tree_batch,
    )
    from fluidframework_tpu.ops.tree_pipeline import (
        pipelined_tree_replay,
        tree_device_cache,
        tree_pack_cache,
    )
    from fluidframework_tpu.service.catchup_cache import DeltaExportCache

    t0 = time.time()
    grow = max(2, TREE_EDITS // 8)
    streams = [synth_tree_messages(i, TREE_EDITS) for i in range(TREE_DOCS)]
    base_docs = [tree_doc(i, s, len(s) - grow)
                 for i, s in enumerate(streams)]
    grown_idx = set(range(0, TREE_DOCS, max(1, GROW_EVERY)))
    grown_docs = [
        tree_doc(i, s, len(s) if i in grown_idx else len(s) - grow)
        for i, s in enumerate(streams)
    ]
    gen_sec = time.time() - t0
    total_ops = sum(len(d.ops) for d in base_docs)
    print(f"tree: generated {TREE_DOCS} docs in {gen_sec:.1f}s",
          file=sys.stderr)

    pack, dev, delta = tree_pack_cache(), tree_device_cache(), \
        DeltaExportCache()
    caches = dict(pack_cache=pack, device_cache=dev, delta_cache=delta)
    cold_dig, cold = _one_pass(pipelined_tree_replay, base_docs,
                               total_ops, caches)
    warm_dig, warm = _one_pass(pipelined_tree_replay, base_docs,
                               total_ops, caches)
    assert warm_dig == cold_dig, "tree warm-exact changed bytes"
    grown_total = sum(len(d.ops) for d in grown_docs)
    grown_dig, grown = _one_pass(pipelined_tree_replay, grown_docs,
                                 grown_total, caches)

    # Parity matrix: caches-off over the WHOLE population, both windows.
    off_base_dig, off_base = _one_pass(pipelined_tree_replay, base_docs,
                                       total_ops, {})
    assert off_base_dig == cold_dig, "tree caches-on != caches-off"
    off_grown_dig, _row = _one_pass(pipelined_tree_replay, grown_docs,
                                    grown_total, {})
    assert off_grown_dig == grown_dig, \
        "tree grown caches-on != caches-off"
    batch_dig = [s.digest()
                 for s in replay_tree_batch(list(grown_docs))]
    assert batch_dig == grown_dig, "pipelined != replay_tree_batch"

    # Forced invalidation: sweep every epoch-keyed tier, then re-fold —
    # still byte-identical (and the tiers legitimately refill).
    delta.invalidate_epoch("other-epoch")
    dev.invalidate_epoch("other-epoch")
    inval_dig, inval = _one_pass(pipelined_tree_replay, grown_docs,
                                 grown_total, caches)
    assert inval_dig == grown_dig, "post-invalidation bytes changed"

    # dds oracle on the deterministic sample (every shape included).
    t0 = time.time()
    n_checked = 0
    for i in range(0, TREE_DOCS, max(1, ORACLE_EVERY)):
        assert grown_dig[i] == \
            oracle_fallback_summary(grown_docs[i]).digest(), (
                f"tree doc {i} ({tree_shape(i)}) != dds oracle")
        n_checked += 1
    oracle_sec = time.time() - t0
    print(f"tree: {n_checked} docs oracle-verified in {oracle_sec:.1f}s",
          file=sys.stderr)

    return {
        "docs": TREE_DOCS,
        "edits_per_doc": TREE_EDITS,
        "grown_docs": len(grown_idx),
        "shapes": {
            s: sum(1 for i in range(TREE_DOCS) if tree_shape(i) == s)
            for s in ("deep-move", "wide-container", "revive",
                      "multi_id_move", "max_depth")
        },
        "gen_sec": round(gen_sec, 1),
        "cold": cold,
        "warm_exact": warm,
        "warm_grown": grown,
        "caches_off": off_base,
        "post_invalidation": inval,
        "fallback_reasons": {
            k: v for k, v in sorted(grown["stats"].items())
            if k.startswith("fallback")
        },
        "pack_cache": pack.stats(),
        "device_cache": dev.stats(),
        "delta_cache": delta.stats(),
        "oracle_checked_docs": n_checked,
        "oracle_every": ORACLE_EVERY,
        "service_catchup": run_tree_catchup_service(),
    }


def build_tree_catchup_corpus(service, n_docs: int, n_edits: int):
    """Seed ``service`` with tree-channel documents: an empty seeded
    summary plus the pinned tree changeset tails appended to the op log
    in the runtime's groupedBatch envelope — the service-shaped twin of
    the tree bench corpus (mirrors ``bench.build_catchup_corpus``)."""
    from fluidframework_tpu.runtime.container import ContainerRuntime

    seeded = ContainerRuntime()
    seeded.create_datastore("ds").create_channel("tree-tpu", "tree")
    seed_tree = seeded.summarize()
    doc_ids = []
    for i in range(n_docs):
        doc_id = f"ctdoc{i}"
        service.storage.upload(doc_id, seed_tree, 0)
        for m in synth_tree_messages(i, n_edits):
            service.oplog.append(doc_id, SequencedMessage(
                seq=m.seq, client_id=m.client_id,
                client_seq=m.client_seq, ref_seq=m.ref_seq,
                min_seq=m.min_seq, type=MessageType.OP,
                contents={"type": "groupedBatch", "ops": [
                    {"ds": "ds", "channel": "tree",
                     "clientSeq": m.client_seq,
                     "contents": m.contents}]},
            ))
        doc_ids.append(doc_id)
    return doc_ids


def run_tree_catchup_service() -> dict:
    """The acceptance-criterion gate: warm tree catch-up through the
    REAL CatchupService serves pure tier-1 — ``cache_hit_rate`` 1.0 and
    ZERO bytes either way on exact hits — byte-identical to the cold
    fold."""
    from fluidframework_tpu.service import LocalOrderingService
    from fluidframework_tpu.service.catchup import CatchupService
    from fluidframework_tpu.tools.bench_harness import benchmark_cold_warm

    n_docs = int(os.environ.get(
        "BENCHK_CATCHUP_DOCS", str(min(TREE_DOCS, 2048))))
    service = LocalOrderingService()
    doc_ids = build_tree_catchup_corpus(service, n_docs, TREE_EDITS)
    svc = CatchupService(service)
    if svc.cache is None:
        print("catchup cache disabled by config gate; skipping tree "
              "cold/warm", file=sys.stderr)
        return {"catchup_docs": n_docs, "skipped": "cache-gate-off"}
    total_ops = n_docs * TREE_EDITS
    results = {}

    def fold():
        results["out"] = svc.catch_up(doc_ids, upload=False)

    before = svc.cache.counters.snapshot()
    pair = benchmark_cold_warm(fold, name="tree-catchup", warm_runs=2,
                               stage=svc.pipeline_stage)
    after = svc.cache.counters.snapshot()
    hit_rate = (after["hits"] - before["hits"]) \
        / max(1, n_docs * pair.warm_runs)
    assert hit_rate >= 1.0, f"tree warm catch-up hit rate {hit_rate}"
    assert pair.warm_h2d_bytes == 0 and pair.warm_d2h_bytes == 0, (
        f"tree warm hit moved bytes: h2d {pair.warm_h2d_bytes} "
        f"d2h {pair.warm_d2h_bytes}")
    print(f"tree catchup: {pair.report()} | hit rate {hit_rate:.3f}",
          file=sys.stderr)
    return {
        "catchup_docs": n_docs,
        "catchup_cold_ops_per_sec": round(total_ops / pair.cold_s, 1),
        "catchup_warm_ops_per_sec": round(total_ops / pair.warm_s, 1),
        "catchup_warm_speedup": round(pair.speedup, 1),
        "cache_hit_rate": round(hit_rate, 4),
        "catchup_warm_h2d_bytes": pair.warm_h2d_bytes,
        "catchup_warm_d2h_bytes": pair.warm_d2h_bytes,
        "catchup_cache": svc.cache.stats(),
        "tree_pack_cache": svc.tree_pack_cache.stats()
        if svc.tree_pack_cache is not None else None,
        "tree_device_cache": svc.tree_device_cache.stats()
        if svc.tree_device_cache is not None else None,
    }


def run_interval_stabbing() -> dict:
    """Cold → warm interval stabbing over 10k folded string docs with
    dense slide cascades, the merge-tree family's interval extraction
    path under the same schema."""
    from fluidframework_tpu.ops.device_cache import DevicePackCache
    from fluidframework_tpu.ops.pipeline import (
        PackCache,
        pipelined_mergetree_replay,
    )
    from fluidframework_tpu.service.catchup_cache import DeltaExportCache

    t0 = time.time()
    grow = max(2, IV_OPS // 8)
    base_docs = [synth_interval_doc(i, IV_OPS, n_msgs=IV_OPS - grow)
                 for i in range(IV_DOCS)]
    grown_idx = set(range(0, IV_DOCS, max(1, GROW_EVERY)))
    grown_docs = [
        synth_interval_doc(
            i, IV_OPS,
            n_msgs=IV_OPS if i in grown_idx else IV_OPS - grow)
        for i in range(IV_DOCS)
    ]
    gen_sec = time.time() - t0
    total_ops = sum(len(d.ops) for d in base_docs)
    iv_ops = sum(
        1 for d in base_docs for m in d.ops
        if m.contents["kind"].startswith("interval"))
    print(f"intervals: generated {IV_DOCS} docs ({iv_ops} interval ops) "
          f"in {gen_sec:.1f}s", file=sys.stderr)

    pack, dev, delta = PackCache(), DevicePackCache(), DeltaExportCache()
    caches = dict(pack_cache=pack, device_cache=dev, delta_cache=delta)
    cold_dig, cold = _one_pass(pipelined_mergetree_replay, base_docs,
                               total_ops, caches)
    warm_dig, warm = _one_pass(pipelined_mergetree_replay, base_docs,
                               total_ops, caches)
    assert warm_dig == cold_dig, "interval warm-exact changed bytes"
    grown_total = sum(len(d.ops) for d in grown_docs)
    grown_dig, grown = _one_pass(pipelined_mergetree_replay, grown_docs,
                                 grown_total, caches)
    off_dig, off = _one_pass(pipelined_mergetree_replay, grown_docs,
                             grown_total, {})
    assert off_dig == grown_dig, "interval caches-on != caches-off"
    delta.invalidate_epoch("other-epoch")
    dev.invalidate_epoch("other-epoch")
    inval_dig, inval = _one_pass(pipelined_mergetree_replay, grown_docs,
                                 grown_total, caches)
    assert inval_dig == grown_dig, \
        "interval post-invalidation bytes changed"

    from fluidframework_tpu.dds.sequence import SharedString

    t0 = time.time()
    n_checked = 0
    for i in range(0, IV_DOCS, max(1, ORACLE_EVERY)):
        replica = SharedString(grown_docs[i].doc_id)
        for m in grown_docs[i].ops:
            replica.process(m, local=False)
        replica.advance(grown_docs[i].final_seq, grown_docs[i].final_msn)
        assert replica.summarize().digest() == grown_dig[i], (
            f"interval doc {i} != SharedString oracle")
        n_checked += 1
    oracle_sec = time.time() - t0
    print(f"intervals: {n_checked} docs oracle-verified in "
          f"{oracle_sec:.1f}s", file=sys.stderr)

    return {
        "docs": IV_DOCS,
        "ops_per_doc": IV_OPS,
        "interval_ops": iv_ops,
        "grown_docs": len(grown_idx),
        "gen_sec": round(gen_sec, 1),
        "cold": cold,
        "warm_exact": warm,
        "warm_grown": grown,
        "caches_off": off,
        "post_invalidation": inval,
        "pack_cache": pack.stats(),
        "device_cache": dev.stats(),
        "delta_cache": delta.stats(),
        "oracle_checked_docs": n_checked,
        "oracle_every": ORACLE_EVERY,
    }


def _run(probe: dict) -> dict:
    import bench

    bench.CURRENT_PHASE["phase"] = "tree-rebase"
    tree = run_tree_rebase()
    bench.CURRENT_PHASE["phase"] = "interval-stabbing"
    intervals = run_interval_stabbing()
    bench.CURRENT_PHASE["phase"] = "done"
    return {
        "metric": METRIC,
        "backend": probe.get("platform", "unknown"),
        "tree_rebase": tree,
        "interval_stabbing": intervals,
    }


def main() -> None:
    import bench

    bench.run_hardened(
        METRIC, _run, DEADLINE,
        skip_base={"tree_rebase": None, "interval_stabbing": None},
    )


if __name__ == "__main__":
    sys.exit(main())
