"""Trace-safety and recompile-hazard rules for the device kernels.

Scope is the kernel layer (``ops/``, ``parallel/``): the files that define
jitted/scanned folds.  A "traced function" is any function that is (a)
decorated with a tracing entrypoint (``jax.jit``, ``jax.vmap``,
``functools.partial(jax.jit, ...)``), or (b) referenced by name as an
argument to one (``jax.lax.scan(step, ...)``, ``jax.jit(f)``), plus every
function lexically nested inside one.  Host syncs, Python control flow on
traced values, and Python loops over ``jnp`` ops inside those bodies are
exactly the hazards that either crash at trace time on real inputs or
silently serialize the device pipeline.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Set, Tuple

from .core import Finding, ImportMap, ModuleContext, Rule, register

KERNEL_SCOPE = (
    "fluidframework_tpu/ops/",
    "fluidframework_tpu/parallel/",
)

#: calls whose function-valued arguments get traced
TRACING_ENTRYPOINTS = {
    "jax.jit",
    "jax.pmap",
    "jax.vmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.lax.scan",
    "jax.lax.map",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.associative_scan",
    "jax.experimental.pallas.pallas_call",
}

_CACHE_DECORATORS = {
    "functools.lru_cache",
    "functools.cache",
    "lru_cache",
    "cache",
}


def _entrypoint_of(imports: ImportMap, node: ast.AST) -> Optional[str]:
    """The tracing entrypoint a decorator/call expression resolves to.

    Handles bare references (``jax.jit``), calls (``jax.jit(...)``) and
    ``functools.partial(jax.jit, ...)``.
    """
    if isinstance(node, ast.Call):
        q = imports.resolve(node.func)
        if q == "functools.partial" and node.args:
            return _entrypoint_of(imports, node.args[0])
        if q in TRACING_ENTRYPOINTS:
            return q
        return None
    q = imports.resolve(node)
    return q if q in TRACING_ENTRYPOINTS else None


def traced_defs(m: ModuleContext) -> List[ast.FunctionDef]:
    """Top-of-chain traced function definitions in the module (nested defs
    inside them are traced too; callers should walk subtrees)."""
    # Names referenced as traceable arguments anywhere in the module.
    traced_names: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Call) and _entrypoint_of(m.imports, node):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    traced_names.add(arg.id)
    out = []
    for node in ast.walk(m.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in traced_names:
            out.append(node)
        elif any(_entrypoint_of(m.imports, d) for d in node.decorator_list):
            out.append(node)
    return out


def _walk_traced(defs: List[ast.FunctionDef]) -> Iterator[Tuple[ast.FunctionDef, ast.AST]]:
    """(owning traced def, node) for every node inside a traced body,
    without double-reporting defs nested in other traced defs."""
    def _contains(outer: ast.AST, inner: ast.AST) -> bool:
        return any(n is inner for n in ast.walk(outer))

    tops = [d for d in defs
            if not any(o is not d and _contains(o, d) for o in defs)]
    seen: Set[int] = set()
    for d in tops:
        for node in ast.walk(d):
            if id(node) not in seen:
                seen.add(id(node))
                yield d, node


def _contains_jnp_call(imports: ImportMap, node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            q = imports.resolve(sub.func)
            if q and (q.startswith("jax.numpy.") or q.startswith("jax.lax.")
                      or q.startswith("jax.ops.")):
                return True
    return False


def _is_shapelike(node: ast.AST) -> bool:
    """Concrete-at-trace-time expressions: shapes, dims, lengths."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "size", "dtype"):
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "len":
            return True
    return False


@register
class HostSyncRule(Rule):
    name = "FL-TRACE-HOSTSYNC"
    severity = "error"
    scope = KERNEL_SCOPE
    description = (
        "host synchronization (.item()/.tolist()/np.asarray/float()) "
        "inside a traced function — blocks the device pipeline or fails "
        "under jit"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        # Messages name the owning traced def: suppression keys are
        # line-independent (rule, path, message), so the owner name keeps
        # a reviewed suppression from masking future findings elsewhere
        # in the same file.
        for owner, node in _walk_traced(traced_defs(m)):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in (
                    "item", "tolist"):
                yield m.finding(
                    self, node,
                    f".{func.attr}() inside traced {owner.name}() forces "
                    "a device->host sync; keep the value on device "
                    "(jnp.where / lax.select) or hoist it out of the fold",
                )
                continue
            q = m.imports.resolve(func)
            if q in ("numpy.asarray", "numpy.array"):
                yield m.finding(
                    self, node,
                    f"{q}() inside traced {owner.name}() materializes "
                    "the tracer on host; use jnp equivalents inside the "
                    "fold and convert after the export fetch",
                )
            elif q in ("float", "int", "bool") and node.args \
                    and not isinstance(node.args[0], ast.Constant) \
                    and not _is_shapelike(node.args[0]):
                yield m.finding(
                    self, node,
                    f"{q}() on a traced value in {owner.name}() forces "
                    "concretization; compute with jnp dtypes on device, "
                    "or mark the argument static if it is genuinely "
                    "host data",
                )


@register
class PythonControlFlowRule(Rule):
    name = "FL-TRACE-PYCOND"
    severity = "error"
    scope = KERNEL_SCOPE
    description = (
        "Python if/while on a traced expression inside a jitted/scanned "
        "function — use lax.cond/lax.select/jnp.where"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for owner, node in _walk_traced(traced_defs(m)):
            if isinstance(node, (ast.If, ast.While)) and \
                    _contains_jnp_call(m.imports, node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                yield m.finding(
                    self, node,
                    f"Python `{kind}` on a traced expression in "
                    f"{owner.name}(); trace-time branching on tracer "
                    "values fails under jit — use lax.cond / lax.select "
                    "/ jnp.where",
                )


@register
class PythonLoopOverJnpRule(Rule):
    name = "FL-TRACE-LOOPJNP"
    severity = "warning"
    scope = KERNEL_SCOPE
    description = (
        "jnp ops inside a Python loop in a traced function unroll at "
        "trace time; prefer lax.scan/vmap (fixed small range(<const>) "
        "unrolls are exempt)"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for owner, node in _walk_traced(traced_defs(m)):
            if isinstance(node, ast.While):
                body = ast.Module(body=node.body, type_ignores=[])
                if _contains_jnp_call(m.imports, body):
                    yield self._flag(m, node, owner, "while")
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if self._is_const_range(node.iter):
                    continue  # deliberate bounded unroll idiom
                body = ast.Module(body=node.body, type_ignores=[])
                if _contains_jnp_call(m.imports, body):
                    yield self._flag(m, node, owner, "for")

    @staticmethod
    def _is_const_range(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "range"
                and all(isinstance(a, ast.Constant) for a in node.args))

    def _flag(self, m: ModuleContext, node: ast.AST,
              owner: ast.FunctionDef, kind: str) -> Finding:
        return m.finding(
            self, node,
            f"jnp ops inside a Python `{kind}` loop in traced "
            f"{owner.name}() unroll at trace time (compile-time blowup, "
            "no fusion across steps); restructure as lax.scan or vmap",
        )


# -- recompile hazards --------------------------------------------------------

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp)
_MUTABLE_ANNOTATIONS = {"list", "dict", "set", "List", "Dict", "Set",
                        "bytearray"}


def _static_params(jit_call: ast.Call) -> Tuple[List[int], List[str]]:
    nums: List[int] = []
    names: List[str] = []
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            nums.extend(_const_ints(kw.value))
        elif kw.arg == "static_argnames":
            names.extend(_const_strs(kw.value))
    return nums, names


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _const_strs(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _annotation_name(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Subscript):
        return _annotation_name(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


@register
class RecompileHazardRule(Rule):
    name = "FL-TRACE-STATIC"
    severity = "error"
    scope = KERNEL_SCOPE
    description = (
        "jit static parameters must be hashable-by-value; mutable "
        "defaults/annotations on statics and jit calls inside loops or "
        "uncached functions recompile (or fail) per call"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        yield from self._check_static_params(m)
        yield from self._check_jit_placement(m)

    # (a) static args whose parameter is provably non-hashable
    def _check_static_params(self, m: ModuleContext) -> Iterator[Finding]:
        defs = {n.name: n for n in ast.walk(m.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in ast.walk(m.tree):
            target: Optional[ast.FunctionDef] = None
            call: Optional[ast.Call] = None
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) and \
                            _entrypoint_of(m.imports, dec) == "jax.jit":
                        target, call = node, dec
                    elif isinstance(dec, ast.Call) and \
                            m.imports.resolve(dec.func) == "functools.partial" \
                            and dec.args and _entrypoint_of(
                                m.imports, dec.args[0]) == "jax.jit":
                        target, call = node, dec
            elif isinstance(node, ast.Call) and \
                    _entrypoint_of(m.imports, node) == "jax.jit" and \
                    node.args and isinstance(node.args[0], ast.Name):
                target = defs.get(node.args[0].id)
                call = node
            if target is None or call is None:
                continue
            yield from self._check_target(m, call, target)

    def _check_target(self, m: ModuleContext, call: ast.Call,
                      fn: ast.FunctionDef) -> Iterator[Finding]:
        nums, names = _static_params(call)
        params = list(fn.args.posonlyargs) + list(fn.args.args)
        defaults = list(fn.args.defaults)
        # right-align defaults against params
        default_of = {}
        for param, d in zip(params[len(params) - len(defaults):], defaults):
            default_of[param.arg] = d
        for kwarg, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
            params.append(kwarg)
            if d is not None:
                default_of[kwarg.arg] = d
        statics = set(names)
        for i in nums:
            if 0 <= i < len(params):
                statics.add(params[i].arg)
        for p in params:
            if p.arg not in statics:
                continue
            d = default_of.get(p.arg)
            if d is not None and isinstance(d, _MUTABLE_LITERALS):
                yield m.finding(
                    self, call,
                    f"jit-static parameter '{p.arg}' of {fn.name}() has a "
                    "non-hashable default; statics are hashed into the "
                    "compile cache key — use a tuple/frozenset or drop "
                    "the static",
                )
            ann = _annotation_name(p.annotation)
            if ann in _MUTABLE_ANNOTATIONS:
                yield m.finding(
                    self, call,
                    f"jit-static parameter '{p.arg}' of {fn.name}() is "
                    f"annotated '{ann}' (unhashable); statics must be "
                    "hashable by value or every call raises/recompiles",
                )

    # (b)/(c) jit created per call
    def _check_jit_placement(self, m: ModuleContext) -> Iterator[Finding]:
        flagged: Set[int] = set()
        for scope in ast.walk(m.tree):
            if isinstance(scope, (ast.For, ast.AsyncFor, ast.While)):
                for node in ast.walk(scope):
                    if isinstance(node, ast.Call) and \
                            m.imports.resolve(node.func) == "jax.jit" and \
                            id(node) not in flagged:
                        flagged.add(id(node))
                        yield m.finding(
                            self, node,
                            "jax.jit(...) constructed inside a loop builds "
                            "a fresh executable (and compile-cache entry) "
                            "per iteration; hoist the jitted callable out",
                        )
        for fn in ast.walk(m.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if any(m.imports.resolve(d) in _CACHE_DECORATORS or
                   (isinstance(d, ast.Call)
                    and m.imports.resolve(d.func) in _CACHE_DECORATORS)
                   for d in fn.decorator_list):
                continue
            for node in _direct_body(fn):
                if isinstance(node, ast.Call) and \
                        m.imports.resolve(node.func) == "jax.jit" and \
                        id(node) not in flagged:
                    flagged.add(id(node))
                    yield m.finding(
                        self, node,
                        f"jax.jit(...) called inside uncached function "
                        f"{fn.name}() returns a fresh callable per call — "
                        "each one re-traces; memoize with "
                        "functools.lru_cache or hoist to module level",
                    )


def _direct_body(fn: ast.FunctionDef) -> Iterator[ast.AST]:
    """Nodes in ``fn``'s own body, excluding nested function scopes."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


# -- donated-buffer discipline ------------------------------------------------


def _donated_positions(jit_call: ast.Call) -> List[int]:
    for kw in jit_call.keywords:
        if kw.arg == "donate_argnums":
            return _const_ints(kw.value)
    return []


def _donating_callables(m: ModuleContext) -> dict:
    """{local name: donated positional indices} for every callable built
    with ``donate_argnums`` — ``f = jax.jit(g, donate_argnums=...)``
    assignments and ``@functools.partial(jax.jit, donate_argnums=...)``
    decorated defs."""
    out: dict = {}
    for node in ast.walk(m.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if _entrypoint_of(m.imports, call) == "jax.jit":
                pos = _donated_positions(call)
                if pos:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            out[target.id] = pos
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and \
                        _entrypoint_of(m.imports, dec) == "jax.jit":
                    pos = _donated_positions(dec)
                    if pos:
                        out[node.name] = pos
    return out


def _target_names(stmt: ast.AST) -> Set[str]:
    """Names a statement (re)binds — the rebind that makes a donated
    reference safe again."""
    names: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                names.add(sub.id)
    return names


@register
class DonatedBufferReadRule(Rule):
    name = "FL-TRACE-DONATE"
    severity = "error"
    scope = KERNEL_SCOPE
    description = (
        "a buffer passed at a donate_argnums position is DEAD after "
        "dispatch (XLA reused its memory) — reading the old reference "
        "later raises at best and aliases garbage at worst; rebind the "
        "result over the donated name"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        donors = _donating_callables(m)
        if not donors:
            return
        for fn in ast.walk(m.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from self._check_fn(m, fn, donors)

    def _check_fn(self, m: ModuleContext, fn: ast.FunctionDef,
                  donors: dict) -> Iterator[Finding]:
        # Per donated-Name call: any Load of that name textually after
        # the call — before a rebinding Store — reads a dead buffer.
        # Known limits (documented in the README): plain Names only
        # (attribute receivers like ``self.ops`` need the caller to swap
        # the reference, which this rule cannot see), and lineno order
        # approximates control flow (a loop re-reading a name bound
        # before the donating call on iteration 2 is not modeled).
        donated: List[tuple] = []  # (name, callee, call node)
        for stmt in _direct_body(fn):
            if not isinstance(stmt, ast.Call) or \
                    not isinstance(stmt.func, ast.Name):
                continue
            callee = stmt.func.id
            if callee not in donors:
                continue
            for i in donors[callee]:
                if i < len(stmt.args) and isinstance(stmt.args[i],
                                                     ast.Name):
                    donated.append((stmt.args[i].id, callee, stmt))
        for name, callee, call in donated:
            # The safe idiom: the donating call's own statement rebinds
            # the name (``x = f(x)``) — the old reference is gone.
            rebound = False
            for stmt in ast.walk(fn):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)) \
                        and any(n is call for n in ast.walk(stmt)) \
                        and name in _target_names(stmt):
                    rebound = True
                    break
            if rebound:
                continue
            end = getattr(call, "end_lineno", call.lineno)
            stores = sorted(
                n.lineno for n in _direct_body(fn)
                if isinstance(n, ast.Name) and n.id == name
                and isinstance(n.ctx, ast.Store) and n.lineno > end
            )
            first_store = stores[0] if stores else None
            for node in _direct_body(fn):
                if isinstance(node, ast.Name) and node.id == name \
                        and isinstance(node.ctx, ast.Load) \
                        and node.lineno > end \
                        and (first_store is None
                             or node.lineno < first_store) \
                        and not any(n is node for n in ast.walk(call)):
                    yield m.finding(
                        self, node,
                        f"'{name}' was donated to {callee}() in "
                        f"{fn.name}() and is dead after dispatch; "
                        "rebind the call's result over the donated "
                        "name (x = f(x)) before any further read",
                    )
                    break
