"""fluidfail — error-taxonomy & cross-process failure-propagation rules.

The serving tier's failure vocabulary is a REGISTRY
(``fluidframework_tpu/protocol/errors.py``): every wire error code is
declared once with its channel (frame / nack / outcome), its typed
exception, and its retryability class (transport / nack-paced /
reconnect / fatal).  Yuan et al. (OSDI'14) found most catastrophic
distributed-system failures start in trivially wrong error-handling
code, and error-propagation bugs are systematically missable by review
— so, like fluiddur did for durability orderings, this family turns the
taxonomy into checked invariants:

``FL-ERR-CODE``
    Registry drift, both directions.  A ``"code"`` literal produced
    anywhere in the package (response dict, ``code=`` keyword,
    ``code = "..."`` assignment, a ``code`` parameter default) and every
    code literal a consumer branches on must be a registered row; a
    registered row must be produced somewhere, and a frame-channel row
    must also be HANDLED somewhere (a driver-side dispatch branch) —
    produced-but-never-handled is an untyped failure crossing the
    process boundary.
``FL-ERR-RETRY``
    A reconnect- or fatal-class exception (per the registry's
    ``EXCEPTIONS`` chains) that a ``RetryPolicy`` site's ``retry_on``
    would catch must appear in that site's ``no_retry`` (or ride
    ``on_fence`` for the ShardFencedError family).  The PR 9
    ConnectionLostError budget-burn bug is this finding.
``FL-ERR-CROSS``
    In a reply-path function (one that builds ``"ok"``-keyed response
    dicts or calls ``send_obj``), a dispatch call must be covered by a
    broad ``except`` that frames a TYPED error response (a ``"code"``
    key) — otherwise a handler fault crosses the process boundary
    unframed and the client cannot classify it.
``FL-ERR-HANDLER``
    A broad ``except`` on a reply path must re-frame an error response,
    report to a telemetry sink, or re-raise — a silent swallow leaves
    the client waiting forever (FL-LEAK-SWALLOW extended to the reply
    contract).
``FL-ERR-RAISE``
    Protocol errors constructed with free-string ``code=`` keywords not
    in the registry (and ``NackError`` built with a code from another
    channel).

Known limits (documented in the README): codes built by string
concatenation or variables are invisible to CODE/RAISE (the registry
convention is literal codes at call sites); RETRY declines at sites
whose ``retry_on``/``no_retry`` tuples are named aliases rather than
inline tuples; CROSS identifies dispatch calls by name convention
(``*dispatch*``, ``_handle*``, executor indirection passing a
``*dispatch*`` callable) and reply paths by shape (``"ok"`` dicts /
``send_obj``), so a renamed dispatcher leaves the rule's scope.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import (Finding, ModuleContext, ProjectContext, ProjectRule,
                   Rule, register)
from .rules_concurrency import _walk_pruned as _fn_walk
from .rules_durability import _const_str, _terminal
from .rules_lifecycle import _dotted, _functions

ERRORS_MODULE = "fluidframework_tpu/protocol/errors.py"

#: retryability classes whose declared recovery is incompatible with an
#: in-place resend — the ones FL-ERR-RETRY polices at retry sites.
_NO_RESEND_CLASSES = ("reconnect", "fatal")

_RETRY_PHRASE = {
    "reconnect": "an in-place resend can never succeed (declared "
                 "recovery: reconnect / re-resolve / rebase)",
    "fatal": "retrying a deterministic rejection burns the budget",
}


# -- registry parsing (the FL-DUR-SEAM/GATE machinery) ------------------------


def _top_dict(tree: ast.Module, name: str) -> Optional[ast.Dict]:
    for node in tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if name in names and isinstance(node.value, ast.Dict):
            return node.value
    return None


def _registered_codes(tree: ast.Module) -> Dict[str, Tuple[int, str]]:
    """WIRE_ERRORS: code -> (line, channel)."""
    out: Dict[str, Tuple[int, str]] = {}
    d = _top_dict(tree, "WIRE_ERRORS")
    if d is None:
        return out
    for key, val in zip(d.keys, d.values):
        lit = _const_str(key)
        if lit is None:
            continue
        channel = ""
        if isinstance(val, ast.Dict):
            for k2, v2 in zip(val.keys, val.values):
                if _const_str(k2) == "channel":
                    channel = _const_str(v2) or ""
        out[lit] = (key.lineno, channel)
    return out


def _registered_exceptions(tree: ast.Module) -> Dict[str, dict]:
    """EXCEPTIONS: name -> {"retry", "parent", "line"}."""
    out: Dict[str, dict] = {}
    d = _top_dict(tree, "EXCEPTIONS")
    if d is None:
        return out
    for key, val in zip(d.keys, d.values):
        lit = _const_str(key)
        if lit is None or not isinstance(val, ast.Dict):
            continue
        row = {"retry": "", "parent": None, "line": key.lineno}
        for k2, v2 in zip(val.keys, val.values):
            k2lit = _const_str(k2)
            if k2lit == "retry":
                row["retry"] = _const_str(v2) or ""
            elif k2lit == "parent":
                row["parent"] = _const_str(v2)
        out[lit] = row
    return out


def _chain(name: str, table: Dict[str, dict]) -> Set[str]:
    """``name`` plus its registered ancestors (cycle-guarded)."""
    seen = [name]
    cur = table.get(name, {}).get("parent")
    while cur is not None and cur in table and cur not in seen:
        seen.append(cur)
        cur = table[cur]["parent"]
    return set(seen)


# -- code-literal scanning ----------------------------------------------------


def _is_code_target(t: ast.AST) -> bool:
    if isinstance(t, ast.Name):
        return t.id == "code"
    if isinstance(t, ast.Subscript):
        return _const_str(t.slice) == "code"
    return False


def _is_code_expr(e: ast.AST) -> bool:
    if isinstance(e, ast.Name):
        return e.id == "code" or e.id.endswith("_code")
    if isinstance(e, ast.Attribute):
        return e.attr == "code"
    if isinstance(e, ast.Subscript):
        return _const_str(e.slice) == "code"
    if isinstance(e, ast.Call):
        return (_terminal(e.func) == "get" and bool(e.args)
                and _const_str(e.args[0]) == "code")
    return False


def _code_sites(tree: ast.Module
                ) -> Tuple[List[Tuple[str, int, str]],
                           List[Tuple[str, int]]]:
    """(produced, consumed) code literals with lines.

    Produced kinds: ``dict`` (``{"code": X}``), ``ctor``/``kw``
    (``code=X`` keyword on an ``*Error`` / other callee), ``assign``
    (``code = X`` / ``out["code"] = X``), ``default`` (a ``code``
    parameter default — ``NackError.__init__``'s "throttled" ships on
    the wire whenever the ctor is called bare).  Consumed: a string
    literal compared against a code-shaped expression (``.code``,
    ``["code"]``, ``.get("code")``, a ``*code`` name)."""
    produced: List[Tuple[str, int, str]] = []
    consumed: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if _const_str(k) == "code":
                    lit = _const_str(v)
                    if lit is not None:
                        produced.append((lit, v.lineno, "dict"))
        elif isinstance(node, ast.Call):
            ctor = (_terminal(node.func) or "").endswith("Error")
            for kw in node.keywords:
                if kw.arg == "code":
                    lit = _const_str(kw.value)
                    if lit is not None:
                        produced.append((lit, kw.value.lineno,
                                         "ctor" if ctor else "kw"))
        elif isinstance(node, ast.Assign):
            lit = _const_str(node.value)
            if lit is not None and any(_is_code_target(t)
                                       for t in node.targets):
                produced.append((lit, node.lineno, "assign"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            pos = list(a.posonlyargs) + list(a.args)
            for arg, dflt in zip(pos[len(pos) - len(a.defaults):],
                                 a.defaults):
                if arg.arg == "code":
                    lit = _const_str(dflt)
                    if lit is not None:
                        produced.append((lit, dflt.lineno, "default"))
            for arg, dflt in zip(a.kwonlyargs, a.kw_defaults):
                if dflt is not None and arg.arg == "code":
                    lit = _const_str(dflt)
                    if lit is not None:
                        produced.append((lit, dflt.lineno, "default"))
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if any(_is_code_expr(s) for s in sides):
                for s in sides:
                    lit = _const_str(s)
                    if lit is not None:
                        consumed.append((lit, s.lineno))
    return produced, consumed


# -- FL-ERR-CODE --------------------------------------------------------------


@register
class ErrCodeRule(ProjectRule):
    """Wire-code registry drift, both directions."""

    name = "FL-ERR-CODE"
    severity = "error"
    description = ("every produced/handled wire error-code literal must be "
                   "a registered protocol/errors.py WIRE_ERRORS row, every "
                   "row must be produced, and every frame-channel row must "
                   "be handled driver-side")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        tree = project.parse(ERRORS_MODULE)
        if tree is None:
            return
        registered = _registered_codes(tree)
        produced_anywhere: Set[str] = set()
        consumed_anywhere: Set[str] = set()
        for rel in project.glob("fluidframework_tpu/**/*.py"):
            if rel == ERRORS_MODULE or "__pycache__" in rel:
                continue
            mod = project.parse(rel)
            if mod is None:
                continue
            produced, consumed = _code_sites(mod)
            for lit, line, kind in produced:
                produced_anywhere.add(lit)
                # ctor sites with an unregistered code are FL-ERR-RAISE's
                # finding — one defect, one rule
                if lit not in registered and kind != "ctor":
                    yield self.project_finding(rel, line, (
                        f"wire code '{lit}' is produced here but not "
                        f"registered in protocol/errors.py WIRE_ERRORS — "
                        f"invisible to the error taxonomy"))
            for lit, line in consumed:
                consumed_anywhere.add(lit)
                if lit not in registered:
                    yield self.project_finding(rel, line, (
                        f"wire code '{lit}' is handled here but not "
                        f"registered in protocol/errors.py WIRE_ERRORS — "
                        f"producer/consumer drift"))
        for code, (line, channel) in sorted(registered.items()):
            if code not in produced_anywhere:
                yield self.project_finding(ERRORS_MODULE, line, (
                    f"registered wire code '{code}' is produced nowhere in "
                    f"the package — dead taxonomy row"))
            elif channel == "frame" and code not in consumed_anywhere:
                yield self.project_finding(ERRORS_MODULE, line, (
                    f"frame code '{code}' is produced but never handled by "
                    f"a driver-side dispatch branch — an untyped failure "
                    f"crossing the process boundary"))


# -- FL-ERR-RAISE -------------------------------------------------------------


@register
class ErrRaiseRule(ProjectRule):
    """Typed errors built with free-string codes."""

    name = "FL-ERR-RAISE"
    severity = "error"
    description = ("a protocol error constructed with a code= keyword must "
                   "use a registered WIRE_ERRORS code, and NackError must "
                   "carry a nack-channel code")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        tree = project.parse(ERRORS_MODULE)
        if tree is None:
            return
        registered = _registered_codes(tree)
        for rel in project.glob("fluidframework_tpu/**/*.py"):
            if rel == ERRORS_MODULE or "__pycache__" in rel:
                continue
            mod = project.parse(rel)
            if mod is None:
                continue
            for node in ast.walk(mod):
                if not isinstance(node, ast.Call):
                    continue
                term = _terminal(node.func) or ""
                if not term.endswith("Error"):
                    continue
                for kw in node.keywords:
                    if kw.arg != "code":
                        continue
                    lit = _const_str(kw.value)
                    if lit is None:
                        continue
                    if lit not in registered:
                        yield self.project_finding(
                            rel, kw.value.lineno, (
                                f"{term} constructed with free-string code "
                                f"'{lit}' — not a registered "
                                f"protocol/errors.py WIRE_ERRORS row"))
                    elif term == "NackError" \
                            and registered[lit][1] != "nack":
                        yield self.project_finding(
                            rel, kw.value.lineno, (
                                f"NackError constructed with '{lit}', a "
                                f"{registered[lit][1]}-channel code — nacks "
                                f"must carry nack-channel codes"))


# -- FL-ERR-RETRY -------------------------------------------------------------


def _tuple_names(expr: Optional[ast.AST]) -> Optional[Set[str]]:
    """Terminal names of an inline exception tuple/list, or None when the
    value is absent or not statically resolvable (a named alias)."""
    if expr is None or not isinstance(expr, (ast.Tuple, ast.List)):
        return None
    out: Set[str] = set()
    for el in expr.elts:
        t = _terminal(el)
        if t is not None:
            out.add(t)
    return out


@register
class ErrRetryRule(ProjectRule):
    """Reconnect/fatal exceptions retried in place."""

    name = "FL-ERR-RETRY"
    severity = "error"
    description = ("a reconnect- or fatal-class exception caught by a "
                   "RetryPolicy site's retry_on must appear in its "
                   "no_retry (or ride on_fence for the fence family)")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        tree = project.parse(ERRORS_MODULE)
        if tree is None:
            return
        table = _registered_exceptions(tree)
        need = sorted(n for n, row in table.items()
                      if row["retry"] in _NO_RESEND_CLASSES)
        for rel in project.glob("fluidframework_tpu/**/*.py"):
            if rel == ERRORS_MODULE or "__pycache__" in rel:
                continue
            mod = project.parse(rel)
            if mod is None:
                continue
            for node in ast.walk(mod):
                if not isinstance(node, ast.Call) \
                        or _terminal(node.func) != "run":
                    continue
                kws = {kw.arg: kw.value for kw in node.keywords if kw.arg}
                if "operation" not in kws:
                    continue  # not a RetryPolicy.run site
                retry_names = _tuple_names(kws.get("retry_on"))
                if retry_names is None:
                    continue  # default retry_on names no registry type
                no_retry = _tuple_names(kws.get("no_retry")) or set()
                fence = kws.get("on_fence")
                has_fence = fence is not None and not (
                    isinstance(fence, ast.Constant)
                    and fence.value is None)
                for exc_name in need:
                    chain = _chain(exc_name, table)
                    if not chain & retry_names:
                        continue
                    if chain & no_retry:
                        continue
                    if has_fence and "ShardFencedError" in chain:
                        continue
                    row = table[exc_name]
                    yield self.project_finding(rel, node.lineno, (
                        f"{row['retry']}-class exception '{exc_name}' is "
                        f"caught by retry_on at this RetryPolicy site but "
                        f"absent from no_retry — "
                        f"{_RETRY_PHRASE[row['retry']]}"))


# -- reply-path shape detection (FL-ERR-CROSS / FL-ERR-HANDLER) ---------------


#: call terminals that push a frame back to a client.
_REPLY_SENDERS = frozenset({"send_obj"})


def _is_reply_fn(fn: ast.AST) -> bool:
    """A function that frames responses: builds ``"ok"``-keyed dicts or
    pushes frames via ``send_obj``."""
    for node in _fn_walk(fn):
        if isinstance(node, ast.Dict) \
                and any(_const_str(k) == "ok" for k in node.keys):
            return True
        if isinstance(node, ast.Call) \
                and _terminal(node.func) in _REPLY_SENDERS:
            return True
    return False


def _dispatchish(call: ast.Call) -> bool:
    term = _terminal(call.func) or ""
    if "dispatch" in term or term.startswith("_handle") or term == "handle":
        return True
    # executor indirection: loop.run_in_executor(None, self._dispatch, ...)
    for arg in call.args:
        t = _terminal(arg)
        if t is not None and "dispatch" in t:
            return True
    return False


def _is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    elts = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    return any(_terminal(el) in ("Exception", "BaseException")
               for el in elts)


def _broad_handler(try_node: ast.Try) -> Optional[ast.ExceptHandler]:
    for h in try_node.handlers:
        if _is_broad(h):
            return h
    return None


def _frames_typed(handler: ast.ExceptHandler) -> bool:
    """The handler builds a typed error response: a dict carrying both
    ``"ok"`` and ``"code"``, or assigns a ``["code"]`` slot."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Dict):
            keys = {_const_str(k) for k in node.keys}
            if "ok" in keys and "code" in keys:
                return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and _const_str(t.slice) == "code":
                    return True
    return False


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    """The handler re-frames, re-raises, or reports to telemetry."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Dict) \
                and any(_const_str(k) == "ok" for k in node.keys):
            return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and _const_str(t.slice) == "code":
                    return True
        if isinstance(node, ast.Call):
            term = _terminal(node.func)
            if term in ("send_obj", "bump"):
                return True
            if term == "send":
                recv = _dotted(node.func.value) \
                    if isinstance(node.func, ast.Attribute) else None
                if recv is not None and "logger" in recv:
                    return True
    return False


# -- FL-ERR-CROSS -------------------------------------------------------------


@register
class ErrCrossRule(Rule):
    """Dispatch faults must cross the boundary framed and typed."""

    name = "FL-ERR-CROSS"
    severity = "error"
    description = ("in a reply-path function, a dispatch call must be "
                   "covered by a broad except that frames a typed (coded) "
                   "error response — otherwise handler faults cross the "
                   "process boundary unframed")
    scope = ("fluidframework_tpu/service/", "fluidframework_tpu/drivers/")

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for fn in _functions(m.tree):
            if not _is_reply_fn(fn):
                continue
            yield from self._check_fn(m, fn)

    def _check_fn(self, m: ModuleContext, fn) -> Iterable[Finding]:
        hits: List[Tuple[ast.Call, Optional[ast.ExceptHandler]]] = []

        def walk(node: ast.AST, cover) -> None:
            if isinstance(node, ast.Call) and _dispatchish(node):
                hits.append((node, cover[-1] if cover else None))
            if isinstance(node, ast.Try):
                bh = _broad_handler(node)
                inner = cover + [bh] if bh is not None else cover
                for st in node.body + node.orelse:
                    walk(st, inner)
                # a fault raised INSIDE a handler or finally is not
                # re-caught by this try
                for h in node.handlers:
                    for st in h.body:
                        walk(st, cover)
                for st in node.finalbody:
                    walk(st, cover)
                return
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                walk(child, cover)

        for st in fn.body:
            walk(st, [])
        for call, handler in hits:
            if handler is None:
                yield m.finding(self, call, (
                    f"a fault can escape this dispatch call in "
                    f"{fn.name}() unframed — no broad except frames a "
                    f"typed error response for the waiting client"))
            elif not _frames_typed(handler):
                yield m.finding(self, call, (
                    f"the broad except covering this dispatch call in "
                    f"{fn.name}() frames no typed error response (no "
                    f"'code') — an untyped failure crosses the process "
                    f"boundary"))


# -- FL-ERR-HANDLER -----------------------------------------------------------


@register
class ErrHandlerRule(Rule):
    """Broad excepts on reply paths must not swallow silently."""

    name = "FL-ERR-HANDLER"
    severity = "error"
    description = ("a broad except in a reply-path function must re-frame "
                   "an error response, report to telemetry, or re-raise — "
                   "a silent swallow leaves the client waiting forever")
    scope = ("fluidframework_tpu/service/", "fluidframework_tpu/drivers/")

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for fn in _functions(m.tree):
            if not _is_reply_fn(fn):
                continue
            for node in _fn_walk(fn):
                if not isinstance(node, ast.Try):
                    continue
                for h in node.handlers:
                    if not _is_broad(h):
                        continue
                    if _handler_reports(h):
                        continue
                    yield m.finding(self, h, (
                        f"broad except on the reply path of {fn.name}() "
                        f"neither re-frames an error response nor reports "
                        f"to telemetry — a swallowed fault leaves the "
                        f"client waiting forever"))
