"""fluidlint command line.

    python -m tools.fluidlint [--root DIR] [--baseline FILE]
                              [--rules FAMILY[,FAMILY...]]
                              [--format text|json | --json] [--list-rules]
                              [--check-baseline] [--write-baseline FILE]
                              [--diff GIT_REF] [--sarif FILE] [paths ...]

Exit codes: 0 clean, 1 unsuppressed findings / stale or invalid baseline /
baseline hygiene failure, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import List, Optional

from .core import (ProjectRule, all_rules, analyze, apply_baseline,
                   baseline_function_hygiene, baseline_rule_hygiene,
                   baseline_skeleton, load_baseline)


def _diff_relpaths(root: pathlib.Path, ref: str) -> Optional[List[str]]:
    """Python files changed since ``ref`` (committed, staged, working
    tree, plus untracked), normalized root-relative — or None when git
    can't answer (not a repo, unknown ref).

    Deleted files are dropped (nothing left to parse); files changed
    outside ``--root`` are dropped the same way an explicit path outside
    the root would be rejected — the findings contract is 'a full run
    restricted to the changed files'."""
    import subprocess

    def _git(*argv: str) -> str:
        return subprocess.run(
            ["git", "-C", str(root)] + list(argv),
            capture_output=True, text=True, check=True,
        ).stdout

    try:
        toplevel = pathlib.Path(_git("rev-parse", "--show-toplevel").strip())
        listed = _git("diff", "--name-only", "-z", ref, "--")
        untracked = _git("ls-files", "--others", "--exclude-standard", "-z")
    except (OSError, subprocess.CalledProcessError):
        return None
    out: List[str] = []
    for name in sorted(set(filter(None, (listed + untracked).split("\0")))):
        if not name.endswith(".py"):
            continue
        p = toplevel / name
        if not p.is_file():
            continue  # deleted since ref
        try:
            out.append(p.resolve().relative_to(root).as_posix())
        except ValueError:
            continue  # changed, but outside --root
    return out


def rule_family(rule) -> str:
    """Family name of a rule, from its defining module
    (``rules_lifecycle`` -> ``lifecycle``)."""
    module = type(rule).__module__.rsplit(".", 1)[-1]
    return module.split("rules_", 1)[-1] if "rules_" in module else module


_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def _sarif_doc(rules, report, entries) -> dict:
    """SARIF 2.1.0 document for one run: the selected rule registry as
    the tool driver, every finding as a result (suppressed ones carry an
    ``external`` suppression with the reviewed reason as justification),
    locations as repo-relative uri + startLine."""
    reasons = {(e.get("rule"), e.get("path"), e.get("message")):
               e.get("reason", "") for e in entries}

    def result(f, suppressed: bool) -> dict:
        r = {
            "ruleId": f.rule,
            "level": f.severity,
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
        }
        if suppressed:
            r["suppressions"] = [{
                "kind": "external",
                "justification": reasons.get(f.suppression_key, ""),
            }]
        return r

    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "fluidlint",
                "rules": [{
                    "id": name,
                    "shortDescription": {
                        "text": " ".join(rules[name].description.split())},
                    "defaultConfiguration": {"level": rules[name].severity},
                } for name in sorted(rules)],
            }},
            "results": [result(f, False) for f in report.unsuppressed]
            + [result(f, True) for f in report.suppressed],
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.fluidlint",
        description="determinism & trace-safety static analysis",
    )
    parser.add_argument("paths", nargs="*",
                        help="repo-relative files to analyze "
                             "(default: the fluidframework_tpu package)")
    parser.add_argument("--root", default=".",
                        help="repo root (default: cwd)")
    parser.add_argument("--baseline", default=None,
                        help="baseline suppression file (JSON)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output (alias for "
                             "--format json)")
    parser.add_argument("--rules", default=None, metavar="FAMILY",
                        help="comma-separated rule ids, rule-id prefixes, "
                             "or family names to run (e.g. 'FL-RACE', "
                             "'FL-DET-CLOCK,FL-TRACE', or 'dur' for the "
                             "durability family); baseline entries for "
                             "other rules are ignored, not stale")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--check-baseline", action="store_true",
                        help="baseline hygiene only: fail when an entry "
                             "names a rule id that is no longer "
                             "registered, or its message references a "
                             "function that no longer exists (no "
                             "analysis pass)")
    parser.add_argument("--write-baseline", metavar="FILE",
                        help="write a baseline skeleton covering current "
                             "findings (reasons left empty for review)")
    parser.add_argument("--diff", metavar="GIT_REF", default=None,
                        help="analyze only files changed since GIT_REF "
                             "(committed + working tree + untracked); "
                             "same findings contract as listing those "
                             "paths explicitly — module rules only, "
                             "project rules stay a full-run cost")
    parser.add_argument("--sarif", metavar="FILE", default=None,
                        help="also write the report as SARIF 2.1.0 (rule "
                             "registry, finding locations, reviewed "
                             "suppressions as external suppression "
                             "objects) — output format and exit code "
                             "are unchanged")
    args = parser.parse_args(argv)
    if args.json:
        args.format = "json"

    rules = all_rules()
    if args.rules:
        families = [f.strip() for f in args.rules.split(",") if f.strip()]
        # A selector matches a rule id exactly, a rule-id prefix, or the
        # rule's family name ('dur' selects every rules_durability rule).
        rules = {name: rule for name, rule in rules.items()
                 if any(name == f or name.startswith(f)
                        or rule_family(rule).startswith(f.lower())
                        for f in families)}
        if not rules:
            print(f"error: --rules {args.rules!r} selects no known rule "
                  "(see --list-rules)", file=sys.stderr)
            return 2

    if args.list_rules:
        # One row per rule: id, family, default severity, one-line doc —
        # the README coverage test keeps the rule-catalog tables in sync
        # with exactly this registry.
        for name, rule in sorted(rules.items()):
            doc = " ".join(rule.description.split())
            print(f"{name} [{rule_family(rule)}/{rule.severity}] {doc}")
        return 0

    root = pathlib.Path(args.root).resolve()
    if not root.is_dir():
        print(f"error: --root {root} is not a directory", file=sys.stderr)
        return 2
    baseline_path = None
    if args.baseline:
        baseline_path = pathlib.Path(args.baseline)
        if not baseline_path.is_absolute():
            baseline_path = root / baseline_path
        # --write-baseline never READS the baseline: bootstrapping the
        # first baseline at the gate's own path must not fail on its
        # not existing yet
        if not baseline_path.is_file() and \
                (args.check_baseline or not args.write_baseline):
            print(f"error: baseline {baseline_path} not found",
                  file=sys.stderr)
            return 2
    relpaths = None
    if args.paths:
        # Normalize to root-relative posix form: rule scopes are prefix
        # matches on that form, so a './' or absolute spelling must not
        # silently fall outside every scope and pass vacuously.
        relpaths = []
        for p in args.paths:
            rp = pathlib.Path(p)
            rp = (rp if rp.is_absolute() else root / rp).resolve()
            expanded = (sorted(rp.rglob("*.py")) if rp.is_dir() else [rp])
            for f in expanded:
                try:
                    relpaths.append(f.relative_to(root).as_posix())
                except ValueError:
                    print(f"error: {p} is outside --root {root}",
                          file=sys.stderr)
                    return 2
    if args.diff is not None:
        if relpaths is not None:
            print("error: --diff and explicit paths are mutually "
                  "exclusive", file=sys.stderr)
            return 2
        relpaths = _diff_relpaths(root, args.diff)
        if relpaths is None:
            print(f"error: git diff against {args.diff!r} failed under "
                  f"{root}", file=sys.stderr)
            return 2
    if args.check_baseline:
        if baseline_path is None:
            print("error: --check-baseline requires --baseline",
                  file=sys.stderr)
            return 2
        entries = load_baseline(baseline_path)
        problems = baseline_rule_hygiene(entries) \
            + baseline_function_hygiene(root, entries)
        for msg in problems:
            print(f"baseline: {msg}")
        print(f"fluidlint: baseline hygiene — {len(problems)} problem(s)")
        return 1 if problems else 0

    findings = analyze(root, relpaths=relpaths, rules=rules)

    if args.write_baseline:
        doc = baseline_skeleton(findings)
        pathlib.Path(args.write_baseline).write_text(
            json.dumps(doc, indent=2, sort_keys=False) + "\n",
            encoding="utf-8")
        print(f"wrote {len(doc['suppressions'])} suppression entries to "
              f"{args.write_baseline} (fill in every 'reason' field)")
        return 0

    entries = all_entries = []
    if baseline_path is not None:
        entries = all_entries = load_baseline(baseline_path)
        if relpaths is not None:
            # Path-scoped run: entries for files outside the analyzed
            # subset — and for project rules, which analyze() skips when
            # given a subset — can't match anything; dropping them keeps
            # the staleness check meaningful instead of spuriously red.
            in_scope = set(relpaths)
            project_rules = {n for n, r in all_rules().items()
                             if isinstance(r, ProjectRule)}
            entries = [e for e in entries
                       if e.get("path") in in_scope
                       and e.get("rule") not in project_rules]
        if args.rules:
            # Rule-scoped run: same logic for entries of unselected rules.
            entries = [e for e in entries if e.get("rule") in rules]
    report = apply_baseline(findings, entries)
    # Rule hygiene checks the FULL registry on purpose: an entry for an
    # unregistered rule is dead weight whether or not this run selected
    # its family — so it runs over the UNFILTERED entry list.
    hygiene = baseline_rule_hygiene(all_entries)
    hygiene += baseline_function_hygiene(root, entries)
    clean = report.clean and not hygiene

    if args.sarif:
        pathlib.Path(args.sarif).write_text(
            json.dumps(_sarif_doc(rules, report, entries), indent=2) + "\n",
            encoding="utf-8")

    if args.format == "json":
        print(json.dumps({
            "unsuppressed": [f.__dict__ for f in report.unsuppressed],
            "suppressed": [f.__dict__ for f in report.suppressed],
            "stale_suppressions": report.stale,
            "invalid_suppressions": report.invalid,
            "baseline_hygiene": hygiene,
        }, indent=2))
        return 0 if clean else 1

    for f in report.unsuppressed:
        print(f.render())
    for msg in report.invalid:
        print(f"baseline: {msg}")
    for msg in hygiene:
        print(f"baseline: {msg}")
    for e in report.stale:
        print(f"baseline: stale suppression (matched no finding): "
              f"[{e.get('rule')}] {e.get('path')}: {e.get('message')}")
    n_err = sum(1 for f in report.unsuppressed if f.severity == "error")
    n_warn = len(report.unsuppressed) - n_err
    print(f"fluidlint: {n_err} error(s), {n_warn} warning(s), "
          f"{len(report.suppressed)} suppressed, "
          f"{len(report.stale)} stale suppression(s), "
          f"{len(hygiene)} hygiene problem(s)")
    return 0 if clean else 1


if __name__ == "__main__":
    raise SystemExit(main())
