"""Replay-determinism rules.

The north star is byte-identical summaries from a 50x catch-up replay; any
wall-clock read, global-PRNG draw, or hash-order-dependent iteration on a
merge/replay path can silently diverge replicas.  These rules cover the
client/service code the replay actually flows through: ``ops/``,
``protocol/``, ``service/``, ``loader/`` (testing/ is exempt — fuzzers are
nondeterministic on purpose, behind explicit seeds).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Set, Tuple

from .core import Finding, ModuleContext, Rule, register

REPLAY_SCOPE = (
    "fluidframework_tpu/ops/",
    "fluidframework_tpu/protocol/",
    "fluidframework_tpu/service/",
    "fluidframework_tpu/loader/",
)

#: absolute wall-clock reads — never appropriate on a replay path; durations
#: belong to time.monotonic()/time.perf_counter() (not flagged) and *schedule*
#: decisions (nack holds, deadlines) must come from an injected clock.
WALL_CLOCK_CALLS = {
    "time.time",
    "time.time_ns",
    "time.ctime",
    "time.localtime",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
}

#: explicitly-seeded constructors and generator APIs stay allowed; everything
#: else under random./numpy.random. draws from ambient global state.
SEEDED_PRNG_ALLOWED = {
    "random.Random",
    "random.SystemRandom",
    "numpy.random.default_rng",
    "numpy.random.Generator",
    "numpy.random.SeedSequence",
    "numpy.random.RandomState",
}


@register
class WallClockRule(Rule):
    name = "FL-DET-CLOCK"
    severity = "error"
    scope = REPLAY_SCOPE
    description = (
        "wall-clock read (time.time/datetime.now) on a replay/merge path; "
        "inject a clock callable or use time.monotonic for durations"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            q = m.imports.resolve(node.func)
            if q in WALL_CLOCK_CALLS:
                yield m.finding(
                    self, node,
                    f"wall-clock read {q}() on a replay path; inject a "
                    "clock callable (default wall clock, deterministic "
                    "under replay) or use time.monotonic for durations",
                )


@register
class GlobalRandomRule(Rule):
    name = "FL-DET-RANDOM"
    severity = "error"
    scope = REPLAY_SCOPE
    description = (
        "unseeded global-PRNG draw (random.* / numpy.random.*); construct "
        "a seeded random.Random / numpy default_rng and thread it through"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            q = m.imports.resolve(node.func)
            if q is None or q in SEEDED_PRNG_ALLOWED:
                continue
            if q.startswith("random.") or q.startswith("numpy.random."):
                yield m.finding(
                    self, node,
                    f"global-PRNG draw {q}() on a replay path; use a "
                    "seeded random.Random / numpy.random.default_rng "
                    "instance threaded from the caller",
                )


# -- set-iteration order ------------------------------------------------------

_ORDERED_CONSUMER_CALLS = {"list", "tuple", "enumerate", "reversed", "iter"}


def _scope_bodies(tree: ast.Module) -> Iterator[Tuple[ast.AST, List[ast.stmt]]]:
    """Every lexical function/class scope plus the module scope."""
    yield tree, tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            yield node, node.body


def _walk_scope(stmts: List[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class
    bodies (those are separate scopes with their own locals).  The
    nested def/class statement itself is yielded; its body is not —
    ``_scope_bodies`` hands each nested function scope its own walk."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _SetTracker:
    """Names in one scope whose *every* assignment is a set expression."""

    def __init__(self, stmts: List[ast.stmt]) -> None:
        set_assigned: Set[str] = set()
        other_assigned: Set[str] = set()
        for node in _walk_scope(stmts):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            else:
                continue
            value = getattr(node, "value", None)
            for t in targets:
                if not isinstance(t, ast.Name):
                    continue
                if value is not None and self._is_set_literal(value):
                    set_assigned.add(t.id)
                else:
                    other_assigned.add(t.id)
        self.set_names = set_assigned - other_assigned

    @staticmethod
    def _is_set_literal(node: ast.AST) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("set", "frozenset"))

    def is_set_expr(self, node: ast.AST) -> bool:
        if self._is_set_literal(node):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.set_names
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
            return (self.is_set_expr(node.left)
                    or self.is_set_expr(node.right))
        return False


@register
class SetIterationRule(Rule):
    name = "FL-DET-SETITER"
    severity = "error"
    scope = REPLAY_SCOPE
    description = (
        "order-dependent iteration over a set (hash order is randomized "
        "per process); sort first, or iterate a list/dict"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for _scope, stmts in _scope_bodies(m.tree):
            tracker = _SetTracker(stmts)
            for node in _walk_scope(stmts):
                yield from self._check_node(m, node, tracker)

    def _check_node(self, m: ModuleContext, node: ast.AST,
                    tracker: _SetTracker) -> Iterator[Finding]:
        if isinstance(node, (ast.For, ast.AsyncFor)):
            if tracker.is_set_expr(node.iter):
                yield self._flag(m, node, "for-loop")
        elif isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                if tracker.is_set_expr(gen.iter):
                    yield self._flag(m, node, "comprehension")
        elif isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Name)
                    and func.id in _ORDERED_CONSUMER_CALLS
                    and node.args
                    and tracker.is_set_expr(node.args[0])):
                yield self._flag(m, node, f"{func.id}()")
            elif (isinstance(func, ast.Name) and func.id == "zip"
                    and any(tracker.is_set_expr(a) for a in node.args)):
                yield self._flag(m, node, "zip()")
            elif (isinstance(func, ast.Attribute) and func.attr == "join"
                    and node.args
                    and tracker.is_set_expr(node.args[0])):
                yield self._flag(m, node, "str.join()")

    def _flag(self, m: ModuleContext, node: ast.AST,
              consumer: str) -> Finding:
        return m.finding(
            self, node,
            f"order-dependent {consumer} over a set; set iteration order "
            "is hash-randomized across processes — wrap in sorted(...) or "
            "keep an ordered container",
        )
