"""fluidlint core — AST rule engine, registry, baseline suppressions.

The analyzer walks the package's Python sources once, parses each file to
an AST, and hands a ``ModuleContext`` to every registered module rule whose
scope covers the file.  Project rules (cross-file contracts like wire
completeness) run once against a ``ProjectContext`` over the repo root.

Findings are identified for baseline purposes by ``(rule, path, message)``
— deliberately *not* by line number, so unrelated edits above a reviewed
suppression don't invalidate it.  Every baseline entry must carry a
non-empty ``reason`` (JSON has no comments; the reason field IS the
comment) and every entry must still match a live finding — stale entries
fail the gate so the baseline can only shrink through review.
"""

from __future__ import annotations

import ast
import builtins
import dataclasses
import io
import json
import pathlib
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning")

#: directories never analyzed by module rules (tests exercise nondeterminism
#: on purpose; the linter must not lint itself into a corner).
DEFAULT_EXEMPT = (
    "fluidframework_tpu/testing/",
    "tests/",
    "tools/",
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str  # repo-relative posix path
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.severity}: {self.message}"

    @property
    def suppression_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)


class ImportMap:
    """Local name → dotted module path, built from a module's imports.

    ``import jax.numpy as jnp`` binds ``jnp -> jax.numpy``;
    ``from time import time`` binds ``time -> time.time``;
    ``import time`` binds ``time -> time``.  Relative imports are
    intra-package and irrelevant to every shipped rule, so they are
    ignored.
    """

    def __init__(self, tree: ast.Module) -> None:
        self.names: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.names[alias.asname] = alias.name
                    else:
                        root = alias.name.split(".")[0]
                        self.names[root] = root
            elif isinstance(node, ast.ImportFrom) and not node.level:
                for alias in node.names:
                    self.names[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted qualified name for a Name/Attribute chain, or None when
        the chain is rooted in something we can't see (a local object, a
        call result)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.names.get(node.id)
        if base is None:
            # Not imported: a builtin or a local binding.  Builtins are
            # meaningful bare ("float", "set"); attribute chains on local
            # objects are opaque.
            if parts:
                return None
            return node.id
        parts.append(base)
        return ".".join(reversed(parts))


@dataclasses.dataclass
class ModuleContext:
    path: str          # repo-relative posix path
    tree: ast.Module
    source: str
    imports: ImportMap
    _comments: Optional[Dict[int, str]] = None

    @property
    def comments(self) -> Dict[int, str]:
        """lineno -> comment text (sans ``#``) for every comment token.

        Rules that honor comment conventions (``# guarded-by: _lock``,
        ``# holds-lock: _lock``) read annotations here; the AST alone
        drops comments.  Lazy — only comment-aware rules pay for the
        tokenize pass."""
        if self._comments is None:
            out: Dict[int, str] = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.source).readline):
                    if tok.type == tokenize.COMMENT:
                        out[tok.start[0]] = tok.string.lstrip("#").strip()
            except (tokenize.TokenError, IndentationError):
                pass  # unparsable tail: annotations simply absent
            self._comments = out
        return self._comments

    def stmt_comment(self, node: ast.AST) -> str:
        """Trailing comment on a statement's first or last line ('' when
        none).  Multi-line statements may carry the annotation on the
        closing line (``)  # guarded-by: _lock``)."""
        first = self.comments.get(getattr(node, "lineno", 0), "")
        if first:
            return first
        return self.comments.get(getattr(node, "end_lineno", 0), "")

    def finding(self, rule: "Rule", node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule.name,
            severity=rule.severity,
            path=self.path,
            line=getattr(node, "lineno", 0),
            message=message,
        )


@dataclasses.dataclass
class ProjectContext:
    root: pathlib.Path

    def parse(self, relpath: str) -> Optional[ast.Module]:
        p = self.root / relpath
        if not p.is_file():
            return None
        return ast.parse(p.read_text(encoding="utf-8"), filename=str(p))

    def glob(self, pattern: str) -> List[str]:
        return sorted(
            p.relative_to(self.root).as_posix()
            for p in self.root.glob(pattern)
        )


class Rule:
    """A per-module rule.  Subclasses set ``name``/``severity``/``scope``
    and implement ``check``."""

    name: str = ""
    severity: str = "error"
    description: str = ""
    #: path prefixes this rule runs on; empty tuple = every analyzed file
    scope: Tuple[str, ...] = ()

    def applies(self, relpath: str) -> bool:
        if any(relpath.startswith(e) for e in DEFAULT_EXEMPT):
            return False
        if not self.scope:
            return True
        return any(relpath.startswith(s) for s in self.scope)

    def check(self, module: ModuleContext) -> Iterable[Finding]:
        raise NotImplementedError

    def project_finding(self, path: str, line: int, message: str) -> Finding:
        return Finding(self.name, self.severity, path, line, message)


class ProjectRule(Rule):
    """A cross-file contract rule; runs once per analysis."""

    def check(self, module: ModuleContext) -> Iterable[Finding]:
        return ()

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and register a rule."""
    inst = cls()
    assert inst.name, f"{cls.__name__} has no name"
    assert inst.severity in SEVERITIES, inst.severity
    assert inst.name not in _REGISTRY, f"duplicate rule {inst.name}"
    _REGISTRY[inst.name] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    from . import rules  # noqa: F401  (registers on first import)

    return dict(_REGISTRY)


# -- analysis drivers ---------------------------------------------------------


def iter_py_files(root: pathlib.Path,
                  packages: Sequence[str] = ("fluidframework_tpu",)
                  ) -> Iterator[str]:
    for pkg in packages:
        base = root / pkg
        if not base.is_dir():
            continue
        for p in sorted(base.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            yield p.relative_to(root).as_posix()


def analyze_source(source: str, relpath: str,
                   rules: Optional[Dict[str, Rule]] = None) -> List[Finding]:
    """Run module rules over one in-memory source (self-test entry)."""
    rules = rules if rules is not None else all_rules()
    tree = ast.parse(source, filename=relpath)
    ctx = ModuleContext(relpath, tree, source, ImportMap(tree))
    out: List[Finding] = []
    for rule in rules.values():
        if isinstance(rule, ProjectRule) or not rule.applies(relpath):
            continue
        out.extend(rule.check(ctx))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def analyze(root: pathlib.Path,
            relpaths: Optional[Sequence[str]] = None,
            rules: Optional[Dict[str, Rule]] = None) -> List[Finding]:
    """Run every applicable rule over the package rooted at ``root``.

    With an explicit ``relpaths`` subset, only module rules run:
    project rules are whole-repo contracts — their findings (and any
    reviewed suppressions for them) don't belong to a path-scoped run.
    """
    rules = rules if rules is not None else all_rules()
    root = pathlib.Path(root)
    files = list(relpaths) if relpaths is not None else list(iter_py_files(root))
    out: List[Finding] = []
    for relpath in files:
        text = (root / relpath).read_text(encoding="utf-8")
        tree = ast.parse(text, filename=relpath)
        ctx = ModuleContext(relpath, tree, text, ImportMap(tree))
        for rule in rules.values():
            if isinstance(rule, ProjectRule) or not rule.applies(relpath):
                continue
            out.extend(rule.check(ctx))
    if relpaths is None:
        project = ProjectContext(root)
        for rule in rules.values():
            if isinstance(rule, ProjectRule):
                out.extend(rule.check_project(project))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


# -- exit-path enumeration ----------------------------------------------------
#
# The fluidleak family (rules_lifecycle.py) asks flow questions the plain
# AST walk cannot answer: "does call X happen on *every* path after call
# Y?".  ``iter_exit_paths`` enumerates a function's control-flow paths —
# normal return, early return, explicit raise, an exception propagating
# out of any call, and fall-through — with ``try``/``except``/``finally``
# composition, so a rule can inspect the event sequence of each exit.
#
# Approximations (deliberate, documented in the fluidlint README):
# loops run zero-or-one times (``while True`` cannot run zero); every
# call may raise; an except handler always catches (flows continue after
# the try — an exception type no handler matches escaping unclosed is
# invisible); nested def/lambda bodies run later and contribute nothing.
# A raising call is recorded as a ``call-raised`` event: it *attempted*
# but did not complete — closers accept attempts, openers do not.


@dataclasses.dataclass(frozen=True)
class PathEvent:
    """One thing that happened along a path: a completed call
    (``"call"``), a call that raised (``"call-raised"``), or entry into a
    with-block (``"with"``, node = the context expression)."""

    kind: str
    node: ast.AST


@dataclasses.dataclass(frozen=True)
class ExitPath:
    """One way out of a function: the ordered events leading there, the
    exit kind (``return`` / ``raise`` / ``exception`` / ``fall``), and
    the exiting node (Return/Raise statement, the raising call, or the
    function itself for fall-through)."""

    events: Tuple[PathEvent, ...]
    kind: str
    node: ast.AST


class _PathBudgetExceeded(Exception):
    pass


def _eval_calls(node: ast.AST) -> List[ast.Call]:
    """Call nodes of one expression in completion order (inner-first).
    Lambda and nested-def bodies run later — skipped."""
    out: List[ast.Call] = []

    def visit(n: ast.AST) -> None:
        if isinstance(n, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        for child in ast.iter_child_nodes(n):
            visit(child)
        if isinstance(n, ast.Call):
            out.append(n)

    visit(node)
    return out


def iter_exit_paths(fn, max_flows: int = 1500) -> Optional[List[ExitPath]]:
    """Every exit path of ``fn``, or ``None`` when the function is too
    branchy for the budget — callers must *decline* (report nothing)
    rather than guess."""
    budget = [max_flows]

    def spend(n: int = 1) -> None:
        budget[0] -= n
        if budget[0] < 0:
            raise _PathBudgetExceeded

    def new_flows() -> Dict[str, list]:
        return {"ret": [], "raise": [], "break": [], "continue": []}

    def merge(into: Dict[str, list], src: Dict[str, list]) -> None:
        for k in ("ret", "raise", "break", "continue"):
            into[k].extend(src[k])

    def eval_expr(prefixes, expr, flows):
        """Thread one expression's calls through every prefix; each call
        forks an exception flow (events exclude nothing — the raising
        call rides along as 'call-raised')."""
        calls = _eval_calls(expr)
        out = []
        for p in prefixes:
            events = p
            for c in calls:
                spend()
                flows["raise"].append(
                    (events + (PathEvent("call-raised", c),), c, "exception"))
                events = events + (PathEvent("call", c),)
            spend()
            out.append(events)
        return out

    def block(stmts, prefixes) -> Dict[str, list]:
        flows = new_flows()
        cur = list(prefixes)
        for stmt in stmts:
            if not cur:
                break  # unreachable tail
            cur = handle(stmt, cur, flows)
        flows["cont"] = cur
        return flows

    def handle(stmt, prefixes, flows):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Pass, ast.Global,
                             ast.Nonlocal, ast.Import, ast.ImportFrom)):
            return prefixes
        if isinstance(stmt, ast.Return):
            pre = eval_expr(prefixes, stmt.value, flows) \
                if stmt.value is not None else prefixes
            for p in pre:
                spend()
                flows["ret"].append((p, stmt))
            return []
        if isinstance(stmt, ast.Raise):
            pre = prefixes
            for part in (stmt.exc, stmt.cause):
                if part is not None:
                    pre = eval_expr(pre, part, flows)
            for p in pre:
                spend()
                flows["raise"].append((p, stmt, "raise"))
            return []
        if isinstance(stmt, ast.Break):
            flows["break"].extend(prefixes)
            return []
        if isinstance(stmt, ast.Continue):
            flows["continue"].extend(prefixes)
            return []
        if isinstance(stmt, ast.If):
            pre = eval_expr(prefixes, stmt.test, flows)
            b = block(stmt.body, pre)
            o = block(stmt.orelse, pre)
            merge(flows, b)
            merge(flows, o)
            return b["cont"] + o["cont"]
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = stmt.test if isinstance(stmt, ast.While) else stmt.iter
            pre = eval_expr(prefixes, head, flows)
            body = block(stmt.body, pre)
            flows["ret"].extend(body["ret"])
            flows["raise"].extend(body["raise"])
            # zero-or-one iterations; `while True` cannot skip the body
            always = isinstance(stmt, ast.While) and \
                isinstance(stmt.test, ast.Constant) and bool(stmt.test.value)
            after = (body["cont"] + body["continue"]
                     + ([] if always else list(pre)))
            if stmt.orelse:
                o = block(stmt.orelse, after)
                merge(flows, o)
                after = o["cont"]
            return after + body["break"]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pre = prefixes
            for item in stmt.items:
                pre = eval_expr(pre, item.context_expr, flows)
                pre = [p + (PathEvent("with", item.context_expr),)
                       for p in pre]
            body = block(stmt.body, pre)
            merge(flows, body)
            return body["cont"]
        if isinstance(stmt, ast.Try):
            local = new_flows()  # this try's own flows, pre-finally
            b = block(stmt.body, prefixes)
            local["ret"].extend(b["ret"])
            local["break"].extend(b["break"])
            local["continue"].extend(b["continue"])
            cont = b["cont"]
            if stmt.orelse:
                o = block(stmt.orelse, cont)
                merge(local, o)
                cont = o["cont"]
            if stmt.handlers:
                # every handler is assumed to catch (see module note);
                # dedupe entry events so N raising calls with identical
                # histories pay for one handler walk
                entries = []
                seen = set()
                for events, _node, _kind in b["raise"]:
                    if events not in seen:
                        seen.add(events)
                        entries.append(events)
                for events in entries:
                    for h in stmt.handlers:
                        hf = block(h.body, [events])
                        merge(local, hf)
                        cont = cont + hf["cont"]
            else:
                local["raise"].extend(b["raise"])
            if stmt.finalbody:
                fin_cache: Dict[tuple, Dict[str, list]] = {}

                def through(events):
                    ff = fin_cache.get(events)
                    if ff is None:
                        ff = block(stmt.finalbody, [events])
                        fin_cache[events] = ff
                        # exits originating IN the finally mask the
                        # in-flight flow (the FINALLY-MASK rule's domain)
                        merge(flows, ff)
                    return ff["cont"]

                out_cont = []
                for events in cont:
                    out_cont.extend(through(events))
                # ret/raise items are (events, node[, kind]) tuples;
                # break/continue items are bare event tuples — escaping
                # to an outer loop carries no exiting node.
                for key in ("ret", "raise"):
                    for item in local[key]:
                        for tail in through(item[0]):
                            flows[key].append((tail,) + tuple(item[1:]))
                for key in ("break", "continue"):
                    for events in local[key]:
                        flows[key].extend(through(events))
                cont = out_cont
            else:
                merge(flows, local)
            return cont
        if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
            # Each case arm branches like an If arm; without a wildcard
            # (`case _:` / bare `case x:`) no arm may match and control
            # falls through.  Flattening arms into straight-line code
            # (the plain-statement fallback) would GUESS — a `return` in
            # one arm would look unconditional to every rule.
            pre = eval_expr(prefixes, stmt.subject, flows)
            out = []
            exhaustive = False
            for case in stmt.cases:
                cpre = pre
                if case.guard is not None:
                    cpre = eval_expr(cpre, case.guard, flows)
                arm = block(case.body, cpre)
                merge(flows, arm)
                out.extend(arm["cont"])
                if case.guard is None and \
                        isinstance(case.pattern, ast.MatchAs) and \
                        case.pattern.pattern is None:
                    exhaustive = True
            if not exhaustive:
                out.extend(pre)
            return out
        # plain statement (Expr/Assign/AugAssign/AnnAssign/Assert/...)
        pre = prefixes
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, (ast.expr_context, ast.operator)):
                continue
            pre = eval_expr(pre, child, flows)
        if isinstance(stmt, ast.Assert):
            for p in pre:
                spend()
                flows["raise"].append((p, stmt, "exception"))
        return pre

    try:
        flows = block(fn.body, [()])
    except (_PathBudgetExceeded, RecursionError):
        return None
    exits: List[ExitPath] = []
    for events in flows["cont"]:
        exits.append(ExitPath(events, "fall", fn))
    for events, node in flows["ret"]:
        exits.append(ExitPath(events, "return", node))
    for events, node, kind in flows["raise"]:
        exits.append(ExitPath(events, kind, node))
    return exits


# -- baseline -----------------------------------------------------------------


@dataclasses.dataclass
class BaselineReport:
    unsuppressed: List[Finding]
    suppressed: List[Finding]
    stale: List[dict]      # entries that matched nothing
    invalid: List[str]     # structural problems (missing reason, ...)

    @property
    def clean(self) -> bool:
        return not (self.unsuppressed or self.stale or self.invalid)


def load_baseline(path: pathlib.Path) -> List[dict]:
    data = json.loads(pathlib.Path(path).read_text(encoding="utf-8"))
    if isinstance(data, dict):
        return list(data.get("suppressions", []))
    raise ValueError(f"{path}: baseline must be an object with 'suppressions'")


def apply_baseline(findings: Sequence[Finding],
                   entries: Sequence[dict]) -> BaselineReport:
    invalid: List[str] = []
    bad_ids = set()
    for i, e in enumerate(entries):
        for field in ("rule", "path", "message"):
            if not isinstance(e.get(field), str) or not e.get(field):
                invalid.append(f"suppression[{i}]: missing '{field}'")
                bad_ids.add(id(e))
        if not str(e.get("reason", "")).strip():
            invalid.append(
                f"suppression[{i}] ({e.get('rule')}, {e.get('path')}): "
                "a reviewed suppression must carry a non-empty 'reason'"
            )
            bad_ids.add(id(e))
    # Invalid entries neither suppress nor count as stale: each problem
    # surfaces exactly once, as the invalid diagnostic.
    keys = {}
    for e in entries:
        if id(e) in bad_ids:
            continue
        k = (e.get("rule"), e.get("path"), e.get("message"))
        if k in keys:
            # a shadowed duplicate would otherwise be dead weight the
            # staleness check can never see
            invalid.append(
                f"duplicate suppression for ({k[0]}, {k[1]}): merge the "
                "entries (one key, one reviewed reason)"
            )
            continue
        keys[k] = e
    matched = set()
    unsuppressed: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        if f.suppression_key in keys:
            suppressed.append(f)
            matched.add(f.suppression_key)
        else:
            unsuppressed.append(f)
    stale = [e for k, e in keys.items() if k not in matched]
    return BaselineReport(unsuppressed, suppressed, stale, invalid)


#: ``name()`` references in finding messages (the function-scoped key
#: convention).  A leading ``.`` or word char means a method/dotted call
#: (``time.time()``, ``.item()``) — those name APIs, not local functions.
_FUNC_REF = re.compile(r"(?<![.\w])([A-Za-z_]\w*)\(\)")
_BUILTIN_NAMES = frozenset(dir(builtins))


def baseline_function_hygiene(root: pathlib.Path,
                              entries: Sequence[dict]) -> List[str]:
    """Entries whose message names a function that no longer exists in
    the entry's file.

    Suppression keys are function-scoped on purpose (messages embed the
    owning ``def``'s name), so when that function is deleted or renamed
    the reviewed reason no longer describes anything real.  Staleness
    catches most of this — the finding disappears with the function —
    but a hygiene failure pinpoints *why* the entry is dead (file gone,
    function gone) instead of a bare "matched no finding", and it runs
    without a full analysis pass (``--check-baseline``)."""
    root = pathlib.Path(root)
    problems: List[str] = []
    parsed: Dict[str, Optional[set]] = {}
    for i, e in enumerate(entries):
        path, msg = e.get("path"), e.get("message")
        if not isinstance(path, str) or not isinstance(msg, str):
            continue  # structurally invalid: apply_baseline reports it
        refs = sorted({name for name in _FUNC_REF.findall(msg)
                       if name not in _BUILTIN_NAMES})
        if not refs:
            continue
        if path not in parsed:
            p = root / path
            if not p.is_file():
                parsed[path] = None
            else:
                try:
                    tree = ast.parse(p.read_text(encoding="utf-8"))
                except SyntaxError:
                    parsed[path] = None  # unparsable: let the gate's
                    # analysis pass surface the real problem
                else:
                    parsed[path] = {
                        n.name for n in ast.walk(tree)
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                    }
        defined = parsed[path]
        if defined is None:
            if not (root / path).is_file():
                problems.append(
                    f"suppression[{i}] ({e.get('rule')}, {path}): file no "
                    "longer exists — delete or re-review the entry")
            continue
        missing = [name for name in refs if name not in defined]
        if missing:
            problems.append(
                f"suppression[{i}] ({e.get('rule')}, {path}): message "
                f"references function(s) {', '.join(missing)} that no "
                "longer exist in that file — the reviewed finding is "
                "gone; delete or re-review the entry")
    return problems


def baseline_rule_hygiene(entries: Sequence[dict],
                          known_rules: Optional[Iterable[str]] = None
                          ) -> List[str]:
    """Entries naming a rule id that is no longer registered.

    The function hygiene check catches vanished *functions*; this
    catches vanished *rules* — a renamed or deleted rule would otherwise
    leave its reviewed suppressions as dead weight the staleness check
    can never see (no rule, no finding, and entries of unselected rules
    are deliberately ignored on ``--rules`` runs).  Always checked
    against the FULL registry, never a family-filtered subset."""
    known = set(known_rules) if known_rules is not None else set(all_rules())
    problems: List[str] = []
    for i, e in enumerate(entries):
        rule = e.get("rule")
        if isinstance(rule, str) and rule and rule not in known:
            problems.append(
                f"suppression[{i}] ({rule}, {e.get('path')}): rule id is "
                "not registered (renamed or deleted rule) — delete the "
                "entry or restore the rule")
    return problems


def baseline_skeleton(findings: Sequence[Finding]) -> dict:
    """A baseline document covering ``findings`` — every entry needs its
    TODO reason replaced by an actual review note before it will pass."""
    seen = set()
    entries = []
    for f in findings:
        if f.suppression_key in seen:
            continue
        seen.add(f.suppression_key)
        entries.append({
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
            "reason": "",
        })
    return {"version": 1, "suppressions": entries}
