"""fluidlint — determinism & trace-safety static analysis for the
fluidframework_tpu package.

CLI: ``python -m tools.fluidlint --baseline lint_baseline.json``
Library: ``analyze(root)``, ``analyze_source(src, relpath)`` for the
self-test fixtures, ``all_rules()`` for the catalog.
"""

from .core import (  # noqa: F401
    Finding,
    ModuleContext,
    ProjectContext,
    ProjectRule,
    Rule,
    all_rules,
    analyze,
    analyze_source,
    apply_baseline,
    baseline_function_hygiene,
    iter_exit_paths,
    baseline_rule_hygiene,
    baseline_skeleton,
    load_baseline,
    register,
)
