"""fluidshape — kernel shape/dtype/bounds and Mosaic-compliance rules.

Scope is the kernel layer (``ops/``, ``parallel/``): the files that build
Pallas blocks, narrow transfer buffers, and jitted entry points.  The two
most expensive bugs in this repo's history were contract violations in
exactly this layer, and both were only caught at runtime on scarce
hardware:

- the Pallas fold failing Mosaic's (8, 128) sublane/lane block rule voided
  the only TPU measurement ever taken (r05) — ``FL-KERN-BLOCK`` is that
  failure as a static invariant, blind to ``interpret=True`` (interpret
  mode accepts blocks Mosaic rejects, which is precisely how r05 shipped);
- the int16 arena-offset overflow (r13) surfaced only when a full-scale
  bench blew the bound — ``FL-KERN-NARROW`` demands every narrow-dtype
  construction be dominated by a declared bound guard.

Annotations (trailing comments on the flagged statement):

- ``# block-rule: <helper>`` — a non-literal BlockSpec/grid dim is rounded
  by ``<helper>``; the name must be a recognized rounding helper.
- ``# bound: <expr>`` — a narrow cast is covered by the named bound guard;
  the expression must reference a guard name (``i16_ok`` / ``I16_LIMIT``
  style) or a module-level definition.
- ``# bucketed-by: <helper>`` — a data-dependent shape expression was
  routed through a bucket ladder upstream of this call.
- ``# masked-by: <mask>`` — a padded plane is masked before the flagged
  reduction; the mask name must exist in the function.

A misspelled or unresolvable annotation is itself a finding — a stale
annotation must fail loudly, not silently suppress.

Known limits (deliberate, documented in the README): shape algebra more
than one helper hop away from a literal is not evaluated (annotate);
rounding helpers are recognized per module plus the shared bucket-ladder
names — a helper aliased through another module needs the annotation; the
sublane requirement uses the int32 (8, 128) tile for every plane (narrower
dtypes need larger sublane multiples — the rounding helpers in use round
to LANE, which satisfies all of them).  Static compliance does NOT replace
the interpret-mode parity tests: Mosaic alignment says a kernel CAN
compile, parity says it computes the right thing.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from .core import (Finding, ModuleContext, ProjectContext, ProjectRule,
                   Rule, register)
from .rules_concurrency import _owner_phrase, _terminal_name, _walk_pruned
from .rules_lifecycle import _functions
from .rules_trace import KERNEL_SCOPE, _entrypoint_of

SUBLANE = 8    # Mosaic second-to-last dim multiple (int32 tile)
LANE = 128     # Mosaic last dim multiple (every dtype)

#: shared bucket-ladder helpers (ops/interning.py, ops/tree_kernel.py) —
#: recognized by name in every kernel module they are imported into.
BUCKET_HELPER_NAMES = frozenset({
    "next_bucket", "next_bucket_fine", "tree_buckets",
})

BLOCK_RE = re.compile(r"block-rule:\s*(\S+)")
BOUND_RE = re.compile(r"bound:\s*(\S.*)")
BUCKET_RE = re.compile(r"bucketed-by:\s*(\S+)")
MASK_RE = re.compile(r"masked-by:\s*(\S+)")

_SIMPLE_STMT = (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr,
                ast.Return, ast.Assert, ast.Raise)


# -- shared shape machinery ---------------------------------------------------


def _scopes(tree: ast.Module) -> Iterator[Tuple[str, ast.AST]]:
    """(owner name, scope node) for the module plus every def; each scope
    is walked pruned, so statements belong to exactly one scope."""
    yield "<module>", tree
    for fn in _functions(tree):
        yield fn.name, fn


def _stmts(scope: ast.AST) -> List[ast.stmt]:
    """Simple statements of one scope in lexical order."""
    out = [n for n in _walk_pruned(scope) if isinstance(n, _SIMPLE_STMT)]
    out.sort(key=lambda n: n.lineno)
    return out


def _module_int_consts(tree: ast.Module) -> Dict[str, int]:
    """Module-level ``NAME = <int literal>`` bindings (DOC_BLOCK, LANE)."""
    out: Dict[str, int] = {}
    for st in tree.body:
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name) \
                and isinstance(st.value, ast.Constant) \
                and type(st.value.value) is int:
            out[st.targets[0].id] = st.value.value
    return out


def _module_names(tree: ast.Module) -> Set[str]:
    """Every module-level binding: defs, classes, assignment targets."""
    out: Set[str] = set()
    for st in tree.body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            out.add(st.name)
        elif isinstance(st, ast.Assign):
            for t in st.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        elif isinstance(st, ast.AnnAssign) and isinstance(st.target, ast.Name):
            out.add(st.target.id)
        elif isinstance(st, (ast.Import, ast.ImportFrom)):
            for alias in st.names:
                out.add((alias.asname or alias.name).split(".")[0])
    return out


def _is_roundup(node: ast.AST) -> bool:
    """The canonical round-up shape: ``((n + m - 1) // m) * m``."""
    return (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult)
            and isinstance(node.left, ast.BinOp)
            and isinstance(node.left.op, ast.FloorDiv)
            and ast.dump(node.right) == ast.dump(node.left.right))


def _returns(fn: ast.AST) -> List[ast.Return]:
    return [n for n in _walk_pruned(fn)
            if isinstance(n, ast.Return) and n.value is not None]


def _mult_of_call(call: ast.Call, helpers: Dict[str, dict],
                  consts: Dict[str, int]) -> Optional[int]:
    """The known rounding multiple of one helper call, or None."""
    info = helpers.get(_terminal_name(call.func) or "")
    if info is None:
        return None
    if info.get("const_mult") is not None:
        return info["const_mult"]
    idx = info.get("mult_param")
    if idx is None:
        return None
    arg: Optional[ast.AST] = None
    if idx < len(call.args):
        arg = call.args[idx]
    else:
        params = info.get("params") or ()
        if idx < len(params):
            for kw in call.keywords:
                if kw.arg == params[idx]:
                    arg = kw.value
    if isinstance(arg, ast.Constant) and type(arg.value) is int:
        return arg.value
    if isinstance(arg, ast.Name):
        return consts.get(arg.id)
    return None


def _rounding_helpers(tree: ast.Module,
                      consts: Dict[str, int]) -> Dict[str, dict]:
    """name -> rounding info for every helper recognized in this module.

    Seeds: the shared bucket ladders (unknown multiple — power-of-two
    ladders bound the jit cache but prove no fixed divisor) and every def
    whose returns all match the canonical round-up shape.  Fixpoint:
    wrappers whose returns are calls (or tuples of calls) to known
    helpers, carrying the resolved multiple per tuple position —
    ``_padded_dims`` style.
    """
    helpers: Dict[str, dict] = {
        name: {"const_mult": None, "mult_param": None,
               "params": (), "tuple": None}
        for name in BUCKET_HELPER_NAMES
    }
    for fn in _functions(tree):
        rets = _returns(fn)
        if not rets or not all(_is_roundup(r.value) for r in rets):
            continue
        params = [a.arg for a in fn.args.args]
        entry = {"const_mult": None, "mult_param": None,
                 "params": tuple(params), "tuple": None}
        mult = rets[0].value.right
        if isinstance(mult, ast.Constant) and type(mult.value) is int:
            entry["const_mult"] = mult.value
        elif isinstance(mult, ast.Name):
            if mult.id in params:
                entry["mult_param"] = params.index(mult.id)
            elif mult.id in consts:
                entry["const_mult"] = consts[mult.id]
        helpers[fn.name] = entry

    changed = True
    while changed:
        changed = False
        for fn in _functions(tree):
            if fn.name in helpers:
                continue
            rets = _returns(fn)
            if not rets:
                continue
            scalar_mults: Set[Optional[int]] = set()
            tuples: List[List[Optional[int]]] = []
            ok = True
            for r in rets:
                v = r.value
                if isinstance(v, ast.Call) \
                        and (_terminal_name(v.func) or "") in helpers:
                    scalar_mults.add(_mult_of_call(v, helpers, consts))
                elif isinstance(v, ast.Tuple) and v.elts and all(
                        isinstance(e, ast.Call)
                        and (_terminal_name(e.func) or "") in helpers
                        for e in v.elts):
                    tuples.append([_mult_of_call(e, helpers, consts)
                                   for e in v.elts])
                else:
                    ok = False
                    break
            if not ok or (scalar_mults and tuples):
                continue
            entry = {"const_mult": None, "mult_param": None,
                     "params": tuple(a.arg for a in fn.args.args),
                     "tuple": None}
            if scalar_mults:
                if len(scalar_mults) == 1:
                    entry["const_mult"] = scalar_mults.pop()
            elif tuples:
                if len({len(t) for t in tuples}) != 1:
                    continue
                entry["tuple"] = [
                    t0 if all(t[i] == t0 for t in tuples) else None
                    for i, t0 in enumerate(tuples[0])
                ]
            helpers[fn.name] = entry
            changed = True
    return helpers


def _shape_env(scope: ast.AST, helpers: Dict[str, dict],
               consts: Dict[str, int]) -> Dict[str, Tuple[str, Optional[int]]]:
    """name -> ("const", value) | ("rounded", multiple or None) for the
    bindings a scope makes that the block rule can reason about.  Module
    int consts are visible in every scope; any other rebind of a tracked
    name drops it (conservative)."""
    env: Dict[str, Tuple[str, Optional[int]]] = {
        k: ("const", v) for k, v in consts.items()
    }
    for st in _stmts(scope):
        if not isinstance(st, (ast.Assign, ast.AnnAssign)):
            continue
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        value = st.value
        if value is None or len(targets) != 1:
            continue
        tgt = targets[0]
        names = []
        if isinstance(tgt, ast.Name):
            names = [tgt.id]
        elif isinstance(tgt, ast.Tuple) and all(
                isinstance(e, ast.Name) for e in tgt.elts):
            names = [e.id for e in tgt.elts]
        for n in names:
            env.pop(n, None)
        if isinstance(tgt, ast.Name):
            if isinstance(value, ast.Constant) and type(value.value) is int:
                env[tgt.id] = ("const", value.value)
            elif isinstance(value, ast.Name) \
                    and env.get(value.id, ("", 0))[0] == "const":
                env[tgt.id] = env[value.id]
            elif isinstance(value, ast.Call) \
                    and (_terminal_name(value.func) or "") in helpers:
                env[tgt.id] = ("rounded",
                               _mult_of_call(value, helpers, consts))
        elif names and isinstance(value, ast.Call) \
                and (_terminal_name(value.func) or "") in helpers:
            tup = helpers[_terminal_name(value.func)].get("tuple")
            for i, n in enumerate(names):
                mult = tup[i] if tup and i < len(tup) else None
                env[n] = ("rounded", mult)
    return env


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is py3.9+
        return "<expr>"


# -- FL-KERN-BLOCK ------------------------------------------------------------


def _dim_verdict(node: ast.AST, req: int,
                 env: Dict[str, Tuple[str, Optional[int]]]
                 ) -> Tuple[str, Optional[str]]:
    """("ok" | "bad" | "unknown", detail) for one BlockSpec dim against a
    required multiple.  "bad" is a PROVEN violation (fires even under an
    annotation); "unknown" needs a helper route or an annotation."""
    if isinstance(node, ast.Constant) and type(node.value) is int:
        if node.value % req == 0:
            return "ok", None
        return "bad", f"literal {node.value} is not a multiple of {req}"
    if isinstance(node, ast.Name):
        entry = env.get(node.id)
        if entry is None:
            return "unknown", None
        kind, val = entry
        if kind == "const":
            if val % req == 0:
                return "ok", None
            return "bad", f"'{node.id}' is {val}, not a multiple of {req}"
        if kind == "rounded":
            if val is None or val % req == 0:
                return "ok", None
            return "bad", (f"'{node.id}' is rounded to multiples of {val}, "
                           f"not of {req}")
    return "unknown", None


def _grid_clean(node: ast.AST,
                env: Dict[str, Tuple[str, Optional[int]]]) -> bool:
    """Grid extents must be built from constants and helper-rounded
    names — floordiv/mult algebra over those is fine."""
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return True
    if isinstance(node, ast.Name):
        return node.id in env
    if isinstance(node, ast.BinOp) \
            and isinstance(node.op, (ast.FloorDiv, ast.Mult)):
        return _grid_clean(node.left, env) and _grid_clean(node.right, env)
    return False


@register
class KernelBlockRule(Rule):
    name = "FL-KERN-BLOCK"
    severity = "error"
    scope = KERNEL_SCOPE
    description = (
        "Pallas BlockSpec/grid dimension not provably Mosaic-aligned "
        "(the 8-sublane / 128-lane block rule) — route it through a "
        "rounding helper or annotate '# block-rule: <helper>'"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        consts = _module_int_consts(m.tree)
        helpers = _rounding_helpers(m.tree, consts)
        out: List[Finding] = []
        for owner, scope in _scopes(m.tree):
            phrase = _owner_phrase(owner)
            env = _shape_env(scope, helpers, consts)
            for st in _stmts(scope):
                ann = BLOCK_RE.search(m.stmt_comment(st))
                ann_ok = bool(ann) and ann.group(1) in helpers
                if ann and not ann_ok:
                    out.append(m.finding(self, st, (
                        f"block-rule annotation names '{ann.group(1)}', "
                        f"which is no recognized rounding helper {phrase} — "
                        f"fix the name or register the helper")))
                for call in (n for n in ast.walk(st)
                             if isinstance(n, ast.Call)):
                    q = m.imports.resolve(call.func)
                    if q == "jax.experimental.pallas.BlockSpec":
                        out.extend(self._check_block(
                            m, st, call, env, phrase, ann_ok))
                    elif q == "jax.experimental.pallas.pallas_call":
                        out.extend(self._check_grid(
                            m, st, call, env, phrase, ann_ok))
        return out

    def _check_block(self, m, st, call, env, phrase, ann_ok):
        shape: Optional[ast.AST] = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg == "block_shape":
                shape = kw.value
        if not isinstance(shape, ast.Tuple) or not shape.elts:
            return
        dims = shape.elts
        for i, dim in enumerate(dims):
            pos = len(dims) - i          # 1 = lane dim, 2 = sublane dim
            if pos > 2:
                continue
            req = LANE if pos == 1 else SUBLANE
            verdict, detail = _dim_verdict(dim, req, env)
            if verdict == "ok" or (verdict == "unknown" and ann_ok):
                continue
            what = detail or (
                f"dim {i} {_expr_text(dim)!r} is not provably a "
                f"multiple of {req}")
            yield m.finding(self, st, (
                f"BlockSpec {what} {phrase} — Mosaic's sublane/lane "
                f"block rule rejects this at compile time on TPU even "
                f"though interpret mode accepts it; route the dim "
                f"through a rounding helper or annotate "
                f"'# block-rule: <helper>'"))

    def _check_grid(self, m, st, call, env, phrase, ann_ok):
        grid: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg == "grid":
                grid = kw.value
        if grid is None:
            return
        extents = grid.elts if isinstance(grid, ast.Tuple) else [grid]
        for i, ext in enumerate(extents):
            if _grid_clean(ext, env) or ann_ok:
                continue
            yield m.finding(self, st, (
                f"pallas_call grid extent {i} {_expr_text(ext)!r} "
                f"{phrase} is not built from rounded or constant dims — "
                f"an unpadded extent silently drops trailing rows; "
                f"round the dims first or annotate "
                f"'# block-rule: <helper>'"))


# -- FL-KERN-NARROW -----------------------------------------------------------


NARROW_DTYPES = {
    "numpy.int8": "int8", "numpy.int16": "int16",
    "jax.numpy.int8": "int8", "jax.numpy.int16": "int16",
}
_NARROW_STRS = {"int8", "int16"}
_CONSTRUCTORS = {
    "zeros", "ones", "empty", "full", "asarray", "ascontiguousarray",
    "array", "arange", "frombuffer", "zeros_like", "ones_like",
    "empty_like", "full_like", "int8", "int16",
}
_ACCUM_OPS = {"sum", "cumsum", "prod", "dot", "matmul", "mean", "einsum",
              "tensordot"}
GUARD_NAME_RE = re.compile(r"^(i(8|16)_ok|I(8|16)_LIMIT)$")


def _narrow_dtype_of(m: ModuleContext, node: ast.AST) -> Optional[str]:
    q = m.imports.resolve(node)
    if q in NARROW_DTYPES:
        return NARROW_DTYPES[q]
    if isinstance(node, ast.Constant) and node.value in _NARROW_STRS:
        return node.value
    return None


def _narrow_construction(m: ModuleContext,
                         call: ast.Call) -> Optional[str]:
    """The narrow dtype a call constructs into, or None."""
    operands = list(call.args) + [kw.value for kw in call.keywords]
    if isinstance(call.func, ast.Attribute) and call.func.attr == "astype":
        for arg in operands:
            dt = _narrow_dtype_of(m, arg)
            if dt:
                return dt
        return None
    q = m.imports.resolve(call.func) or ""
    if not (q.startswith("numpy.") or q.startswith("jax.numpy.")):
        return None
    tail = q.rsplit(".", 1)[-1]
    if tail not in _CONSTRUCTORS:
        return None
    if tail in _NARROW_STRS:
        return tail
    for arg in operands:
        dt = _narrow_dtype_of(m, arg)
        if dt:
            return dt
    return None


def _is_guard(m: ModuleContext, node: ast.AST) -> bool:
    """A declared bound guard: the ``i16_ok`` / ``I16_LIMIT`` pack-time
    idiom, an ``iinfo`` bounds lookup, or a dtype comparison (the buffer
    is narrow ALREADY — relayout, not narrowing)."""
    if isinstance(node, ast.Name) and GUARD_NAME_RE.match(node.id):
        return True
    if isinstance(node, ast.Attribute) and GUARD_NAME_RE.match(node.attr):
        return True
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and GUARD_NAME_RE.match(node.value):
        return True
    if isinstance(node, ast.Call) \
            and (_terminal_name(node.func) or "") == "iinfo":
        return True
    if isinstance(node, ast.Compare):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr == "dtype":
                return True
    return False


def _bound_annotation_valid(expr: str, module_names: Set[str]) -> bool:
    idents = re.findall(r"[A-Za-z_]\w*", expr)
    return any(GUARD_NAME_RE.match(t) or t == "iinfo" or t in module_names
               for t in idents)


@register
class KernelNarrowRule(Rule):
    name = "FL-KERN-NARROW"
    severity = "error"
    scope = KERNEL_SCOPE
    description = (
        "narrow-dtype (int8/int16) construction or accumulation with no "
        "dominating bound guard — declare the i16_ok/I16_LIMIT pack-time "
        "check or annotate '# bound: <expr>'"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        names = _module_names(m.tree)
        out: List[Finding] = []
        for owner, scope in _scopes(m.tree):
            phrase = _owner_phrase(owner)
            guard_line: Optional[int] = None
            for n in _walk_pruned(scope):
                if _is_guard(m, n):
                    line = getattr(n, "lineno", None)
                    if line is not None and (guard_line is None
                                             or line < guard_line):
                        guard_line = line
            narrow_names: Dict[str, int] = {}
            for st in _stmts(scope):
                stmt_dtype: Optional[str] = None
                for call in (n for n in ast.walk(st)
                             if isinstance(n, ast.Call)):
                    dt = _narrow_construction(m, call)
                    if dt:
                        stmt_dtype = dt
                        break
                accum = None
                if stmt_dtype is None:
                    accum = self._accumulation(st, narrow_names)
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            if stmt_dtype:
                                narrow_names[t.id] = st.lineno
                            else:
                                narrow_names.pop(t.id, None)
                if stmt_dtype is None and accum is None:
                    continue
                if guard_line is not None and guard_line <= st.lineno:
                    continue
                ann = BOUND_RE.search(m.stmt_comment(st))
                if ann:
                    if _bound_annotation_valid(ann.group(1), names):
                        continue
                    out.append(m.finding(self, st, (
                        f"bound annotation {ann.group(1)!r} {phrase} "
                        f"references no bound guard or module name — "
                        f"fix the reference so the declared bound is "
                        f"checkable")))
                    continue
                if stmt_dtype:
                    out.append(m.finding(self, st, (
                        f"narrow {stmt_dtype} construction {phrase} has "
                        f"no dominating bound guard — values over the "
                        f"{stmt_dtype} limit wrap silently; add the "
                        f"i16_ok/I16_LIMIT pack-time check or a "
                        f"'# bound: <expr>' annotation")))
                else:
                    out.append(m.finding(self, st, (
                        f"accumulating op on narrow lanes '{accum}' "
                        f"{phrase} with no dominating bound guard — "
                        f"sums over narrow lanes overflow long before "
                        f"the inputs do; widen first or declare the "
                        f"bound")))
        return out

    @staticmethod
    def _accumulation(st: ast.stmt,
                      narrow_names: Dict[str, int]) -> Optional[str]:
        for call in (n for n in ast.walk(st) if isinstance(n, ast.Call)):
            if (_terminal_name(call.func) or "") not in _ACCUM_OPS:
                continue
            operands: List[ast.AST] = list(call.args)
            if isinstance(call.func, ast.Attribute):
                operands.append(call.func.value)
            for op in operands:
                for sub in ast.walk(op):
                    if isinstance(sub, ast.Name) and sub.id in narrow_names \
                            and narrow_names[sub.id] < st.lineno:
                        return sub.id
        return None


# -- FL-KERN-BUCKET -----------------------------------------------------------


_JIT_ENTRYPOINTS = {"jax.jit", "jax.pmap"}


def _jitted_names(m: ModuleContext) -> Tuple[Set[str], Set[str]]:
    """(jitted callables, jit factories) bound at module level: decorated
    defs, ``name = jax.jit(f)`` bindings, and defs whose every return is
    a jit application (the lru-cached factory idiom)."""
    jitted: Set[str] = set()
    factories: Set[str] = set()
    for node in ast.walk(m.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_entrypoint_of(m.imports, d) in _JIT_ENTRYPOINTS
                   for d in node.decorator_list):
                jitted.add(node.name)
            else:
                rets = _returns(node)
                if rets and all(
                        isinstance(r.value, ast.Call)
                        and _entrypoint_of(m.imports, r.value)
                        in _JIT_ENTRYPOINTS for r in rets):
                    factories.add(node.name)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _entrypoint_of(m.imports, node.value) in _JIT_ENTRYPOINTS:
            jitted.add(node.targets[0].id)
    return jitted, factories


def _shape_tainted(node: ast.AST, dirty: Set[str],
                   helpers: Dict[str, dict]) -> bool:
    """True when an expression carries a data-dependent extent (``len``,
    ``.shape``, or a tainted name) not routed through a bucket helper."""
    if isinstance(node, ast.Call):
        if (_terminal_name(node.func) or "") in helpers:
            return False  # routed: the ladder bounds the jit cache
        if isinstance(node.func, ast.Name) and node.func.id == "len":
            return True
    if isinstance(node, ast.Attribute) and node.attr == "shape":
        return True
    if isinstance(node, ast.Name) and node.id in dirty:
        return True
    return any(_shape_tainted(c, dirty, helpers)
               for c in ast.iter_child_nodes(node))


@register
class KernelBucketRule(Rule):
    name = "FL-KERN-BUCKET"
    severity = "error"
    scope = KERNEL_SCOPE
    description = (
        "jitted entry point reached with a data-dependent shape "
        "expression not routed through a bucket-ladder helper — every "
        "distinct extent recompiles; bucket it or annotate "
        "'# bucketed-by: <helper>'"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        consts = _module_int_consts(m.tree)
        helpers = _rounding_helpers(m.tree, consts)
        jitted, factories = _jitted_names(m)
        if not jitted and not factories:
            return ()
        valid_ann = set(helpers) | {
            fn.name for fn in _functions(m.tree)}
        out: List[Finding] = []
        for owner, scope in _scopes(m.tree):
            if owner in jitted:
                continue  # inside a traced body shapes are already static
            phrase = _owner_phrase(owner)
            dirty: Set[str] = set()
            for st in _stmts(scope):
                self._flag_calls(m, st, jitted, factories, dirty, helpers,
                                 valid_ann, phrase, out)
                if isinstance(st, ast.Assign):
                    tainted = _shape_tainted(st.value, dirty, helpers)
                    for t in st.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                if tainted:
                                    dirty.add(n.id)
                                else:
                                    dirty.discard(n.id)
        return out

    def _flag_calls(self, m, st, jitted, factories, dirty, helpers,
                    valid_ann, phrase, out):
        ann = BUCKET_RE.search(m.stmt_comment(st))
        if ann and ann.group(1) not in valid_ann:
            out.append(m.finding(self, st, (
                f"bucketed-by annotation names '{ann.group(1)}', which "
                f"is no recognized bucket or rounding helper {phrase} — "
                f"fix the name so the routing claim is checkable")))
            ann = None
        for call in (n for n in ast.walk(st) if isinstance(n, ast.Call)):
            target = None
            if isinstance(call.func, ast.Name) and call.func.id in jitted:
                target = call.func.id
            elif isinstance(call.func, ast.Call) \
                    and (_terminal_name(call.func.func) or "") in factories:
                target = _terminal_name(call.func.func)
            if target is None:
                continue
            operands = list(call.args) + [kw.value for kw in call.keywords]
            for op in operands:
                if not _shape_tainted(op, dirty, helpers):
                    continue
                if ann:
                    break
                out.append(m.finding(self, st, (
                    f"jitted entry '{target}' called with data-dependent "
                    f"shape expression {_expr_text(op)!r} {phrase} — "
                    f"every distinct value compiles a fresh executable; "
                    f"route it through a bucket ladder or annotate "
                    f"'# bucketed-by: <helper>'")))
                break


# -- FL-KERN-PAD --------------------------------------------------------------


_REDUCERS = {"sum", "cumsum", "prod", "dot", "matmul", "mean", "einsum",
             "tensordot"}


def _is_pad_call(call: ast.Call) -> bool:
    name = _terminal_name(call.func) or ""
    return "pad" in name.lower()


def _contains_pad_call(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and _is_pad_call(n)
               for n in ast.walk(node))


def _mentions(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


def _masked_expr(node: ast.AST) -> bool:
    """A mask applied in the consuming expression itself: a ``where``
    call or a mask multiply."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call) \
                and "where" in (_terminal_name(n.func) or ""):
            return True
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mult):
            return True
    return False


@register
class KernelPadRule(Rule):
    name = "FL-KERN-PAD"
    severity = "error"
    scope = KERNEL_SCOPE
    description = (
        "plane built by a pad-producing helper reaches a "
        "reduction/digest with no mask in between — pad rows perturb "
        "the result; mask first or annotate '# masked-by: <mask>'"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        out: List[Finding] = []
        for owner, scope in _scopes(m.tree):
            phrase = _owner_phrase(owner)
            local_names = {n.id for n in _walk_pruned(scope)
                           if isinstance(n, ast.Name)}
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                a = scope.args
                local_names.update(p.arg for p in (
                    a.args + a.posonlyargs + a.kwonlyargs))
            padded: Dict[str, int] = {}
            for st in _stmts(scope):
                self._flag_consumption(m, st, padded, local_names,
                                       phrase, out)
                if isinstance(st, ast.Assign):
                    is_pad = _contains_pad_call(st.value)
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            if is_pad:
                                padded[t.id] = st.lineno
                            else:
                                # any rewrite (masking included) clears
                                padded.pop(t.id, None)
        return out

    def _flag_consumption(self, m, st, padded, local_names, phrase, out):
        ann = MASK_RE.search(m.stmt_comment(st))
        if ann and ann.group(1) not in local_names:
            out.append(m.finding(self, st, (
                f"masked-by annotation names '{ann.group(1)}', which is "
                f"no name {phrase} — fix the reference so the masking "
                f"claim is checkable")))
            ann = None
        for call in (n for n in ast.walk(st) if isinstance(n, ast.Call)):
            tail = (_terminal_name(call.func) or "").lower()
            if tail not in _REDUCERS and "digest" not in tail \
                    and "hash" not in tail:
                continue
            operands: List[ast.AST] = list(call.args)
            if isinstance(call.func, ast.Attribute):
                operands.append(call.func.value)
            for op in operands:
                hit = next((name for name, line in padded.items()
                            if line < st.lineno and _mentions(op, name)),
                           None)
                if hit is None and _contains_pad_call(op):
                    hit = _expr_text(op)
                if hit is None or _masked_expr(op) or ann:
                    continue
                out.append(m.finding(self, st, (
                    f"padded plane '{hit}' reaches reduction '{tail}' "
                    f"{phrase} with no mask in between — pad rows "
                    f"contribute to the result; mask the plane or "
                    f"annotate '# masked-by: <mask>'")))


# -- FL-KERN-FAMILY -----------------------------------------------------------


_FAMILY_PATH = "fluidframework_tpu/ops/family.py"
_PIPELINE_PATH = "fluidframework_tpu/ops/pipeline.py"
_MESH_PATH = "fluidframework_tpu/parallel/shard.py"
_CANON_STAGES = ("pack", "upload", "dispatch", "device_wait", "download",
                 "extract")
_MESH_HOOKS = ("make_pad", "pad_token", "dispatch_sharded")


@register
class KernelFamilyRule(ProjectRule):
    name = "FL-KERN-FAMILY"
    severity = "error"
    scope = KERNEL_SCOPE
    description = (
        "KernelFamily registry drift: a registered family omits a "
        "descriptor hook, serves a non-canonical stage schema, or the "
        "mesh twin lacks the single-device hooks"
    )

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        fam_tree = project.parse(_FAMILY_PATH)
        if fam_tree is None:
            return
        fields: List[str] = []
        for node in ast.walk(fam_tree):
            if isinstance(node, ast.ClassDef) and node.name == "KernelFamily":
                fields = [st.target.id for st in node.body
                          if isinstance(st, ast.AnnAssign)
                          and isinstance(st.target, ast.Name)]
        if not fields:
            return
        for relpath in project.glob("fluidframework_tpu/**/*.py"):
            if not self.applies(relpath):
                continue
            tree = project.parse(relpath)
            if tree is None:
                continue
            for call in (n for n in ast.walk(tree)
                         if isinstance(n, ast.Call)
                         and _terminal_name(n.func) == "KernelFamily"):
                got = set(fields[:len(call.args)])
                got.update(kw.arg for kw in call.keywords if kw.arg)
                for f in fields:
                    if f not in got:
                        yield self.project_finding(relpath, call.lineno, (
                            f"KernelFamily registration omits descriptor "
                            f"hook '{f}' — every registered family must "
                            f"populate every hook so the pipeline never "
                            f"branches on family identity"))
                for kw in call.keywords:
                    if kw.arg and kw.arg not in fields:
                        yield self.project_finding(relpath, call.lineno, (
                            f"KernelFamily registration passes unknown "
                            f"hook '{kw.arg}' — registry and descriptor "
                            f"have drifted"))
                    elif kw.arg in _MESH_HOOKS \
                            and isinstance(kw.value, ast.Constant) \
                            and kw.value.value is None:
                        yield self.project_finding(relpath, call.lineno, (
                            f"KernelFamily mesh hook '{kw.arg}' is None — "
                            f"the mesh twin must register the same hooks "
                            f"as the single-device path (stage-schema "
                            f"parity)"))
        yield from self._check_stages(project)

    def _check_stages(self, project: ProjectContext) -> Iterator[Finding]:
        tree = project.parse(_PIPELINE_PATH)
        if tree is not None:
            stage_keys: Optional[Tuple] = None
            line = 1
            for st in tree.body:
                if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                        and isinstance(st.targets[0], ast.Name) \
                        and st.targets[0].id == "STAGE_KEYS" \
                        and isinstance(st.value, (ast.Tuple, ast.List)):
                    line = st.lineno
                    if all(isinstance(e, ast.Constant) for e in st.value.elts):
                        stage_keys = tuple(e.value for e in st.value.elts)
            if stage_keys is not None and stage_keys != _CANON_STAGES:
                yield self.project_finding(_PIPELINE_PATH, line, (
                    f"STAGE_KEYS {stage_keys!r} diverges from the "
                    f"canonical stage schema {_CANON_STAGES!r} — every "
                    f"family's pipeline must serve the same seed_stage "
                    f"keys"))
        mesh = project.parse(_MESH_PATH)
        if mesh is not None:
            uses = any(
                (isinstance(n, ast.Name) and n.id == "seed_stage")
                or (isinstance(n, ast.Attribute) and n.attr == "seed_stage")
                for n in ast.walk(mesh))
            if not uses:
                yield self.project_finding(_MESH_PATH, 1, (
                    "the mesh twin never seeds the canonical stage "
                    "schema — sharded runs would record a different "
                    "stage shape than single-device"))
