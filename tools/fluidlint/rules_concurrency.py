"""fluidrace — lock-discipline & atomicity rules for the threaded serving
path.

PR 3 made serving genuinely concurrent: executor threads in
``service/server.py``, single-flight fold caching in
``service/catchup_cache.py``, reader/dispatcher threads in
``drivers/network_driver.py``, and locks in ``ops/pipeline.py``,
``protocol/summary.py`` and ``service/orderer.py``.  Nothing *enforced*
that shared state is touched under the right lock — a data race survives
every deterministic tier-1 test by definition.  In the spirit of Infer's
RacerD (compositional, per-class reasoning) and Clang thread-safety
analysis (``GUARDED_BY`` declarations), this family checks the lock
discipline statically, per class, over the plain AST.

The class model
---------------

A class is **thread-visible** when its state can be reached from more
than one thread: it creates ``threading.Thread``s, owns
``Lock``/``RLock``/``Condition``/``Event`` members, or acquires a lock
attribute it inherits (``with self._lock:`` with no local assignment).
Only thread-visible classes are analyzed — single-threaded classes stay
annotation-free and silent.

The guarded-by relation maps attributes to the lock that protects them:

- **declared**: a trailing comment ``# guarded-by: <lock>`` on the
  attribute's assignment (conventionally in ``__init__``; for multi-line
  assignments the closing line works too);
- **inferred**: every write outside ``__init__`` happens under the same
  held lock — the attribute is adopted as guarded by it.

A method is *lock-held* (its body runs with a lock already acquired by
its callers) when its name ends in ``_locked`` (all class locks assumed)
or its ``def`` line carries ``# holds-lock: <lock>[, <lock>]``.  Held
methods are exempt from the outside-lock check and their writes count as
locked for inference.  Nested functions/lambdas defined under a ``with``
run *later*, possibly on another thread — they are analyzed with an
empty held set.

The event-loop model (ISSUE 18)
-------------------------------

A class that constructs a ``selectors.*`` selector is a **loop class**:
its methods run on the event-loop thread by default (opt out with an
``# off-loop`` comment on the method header), and any method anywhere
may opt in with ``# on-loop``.  Inside an on-loop method, a call from
the blocking blocklist is a finding even with NO lock held — one
blocking callback stalls every connection the loop owns.  The loop's
own non-blocking socket primitives (``recv``/``accept``/``connect_ex``)
are exempt: on the loop they are non-blocking by construction.  Nested
functions and lambdas are excluded (they run deferred — handing work to
a pool is exactly the prescribed fix).

Known limits (document, don't pretend): the analysis is per class and
per file — cross-object guarding (``self.service.state_lock`` protecting
``self.service.handle_tenants``) and inherited annotations are invisible,
and interprocedural lock flow is only visible through the two held-method
conventions above.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterable, Iterator, List, Optional, Set, Tuple

from .core import Finding, ModuleContext, Rule, register

#: serving paths: the places where an unbounded wait hangs a client- or
#: server-side thread that traffic depends on.
SERVING_SCOPE = (
    "fluidframework_tpu/service/",
    "fluidframework_tpu/drivers/",
)

#: lock constructors → kind (re-entrancy matters for self-acquisition)
LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
}
EVENT_CTORS = ("threading.Event", "threading.Barrier")
#: Condition.wait() REQUIRES its lock held (it releases internally) — the
#: blocking rule must not flag the canonical pattern, but a timeout-less
#: Condition.wait() still hangs a crashed-notifier waiter.
CONDITION_CTOR = "threading.Condition"
THREAD_CTOR = "threading.Thread"

#: PROJECT-CONFIGURABLE blocklist: terminal call names known to block —
#: RPC round-trips, device folds, packs, socket reads.  Extend this set
#: when a new slow entry point appears; holding any lock across one of
#: these stalls every thread contending for that lock.
BLOCKING_CALLS = {
    "request",               # _RpcClient.request — network round-trip
    "run_in_executor",
    "readexactly", "recv", "accept", "connect_ex",
    "pack_mergetree_batch",  # host pack: the serving floor's busy stage
    "replay_export",         # device dispatch
    "export_to_numpy",       # blocking d2h fetch
    "catch_up",              # a whole bulk fold
    "urlopen", "sleep",
}

#: Blocklist calls EXEMPT inside on-loop methods: the loop's own socket
#: primitives run against non-blocking sockets there by construction
#: (they still count under a held lock — that check is about stalls of
#: lock contenders, not of the loop).
LOOP_EXEMPT_CALLS = {"recv", "accept", "connect_ex"}

ON_LOOP_RE = re.compile(r"\bon-loop\b")
OFF_LOOP_RE = re.compile(r"\boff-loop\b")

#: attribute calls that mutate their receiver in place
MUTATORS = {
    "append", "appendleft", "add", "pop", "popitem", "popleft", "clear",
    "update", "remove", "discard", "setdefault", "extend", "insert",
}

GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_]\w*)")
HOLDS_LOCK_RE = re.compile(r"holds-lock:\s*([A-Za-z_][\w, ]*)")

_CTOR_EXEMPT = ("__init__", "__new__", "__del__")


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` when ``node`` is exactly ``self.X``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


@dataclasses.dataclass
class _Access:
    method: str
    attr: str
    write: bool
    held: FrozenSet[str]
    node: ast.AST
    deferred: bool  # inside a nested def/lambda (runs later, elsewhere)


@dataclasses.dataclass
class _LockEvent:
    """One lock acquisition site (a ``with`` item or ``.acquire()``)."""

    method: str
    lock: str
    held_before: FrozenSet[str]
    node: ast.AST


@dataclasses.dataclass
class _BlockingCall:
    method: str
    name: str
    held: FrozenSet[str]
    node: ast.AST


class _ClassModel:
    """Everything the rule family needs to know about one class."""

    def __init__(self, m: ModuleContext, cls: ast.ClassDef) -> None:
        self.cls = cls
        self.name = cls.name
        self.locks: Dict[str, str] = {}       # lock attr -> kind
        self.declared: Dict[str, str] = {}    # attr -> lock (annotations)
        self.bad_declarations: List[Tuple[ast.AST, str]] = []
        self.spawns_threads = False
        self.has_events = False
        self.loop_class = False
        # Event names visible module-wide: `.wait()` on one of these
        # while a lock is held is a blocking call (Condition names are
        # NOT here — Condition.wait requires its lock held).
        self._module_events, _, _ = _module_waitables(m)
        self.methods: List[ast.FunctionDef] = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self._collect_members(m)
        self._collect_declarations(m)
        # A typo'd '# holds-lock:' must be as loud as a typo'd
        # '# guarded-by:': an unknown name would otherwise silently
        # exempt nothing while the author believes the method is covered
        # (and all-writes inference quietly declines).
        self.bad_holds: List[Tuple[ast.AST, str]] = []
        for fn in self.methods:
            names = self._holds_declaration(fn, m)
            for lock in sorted((names or set()) - set(self.locks)):
                self.bad_holds.append((fn, lock))
        # an explicit '# on-loop' opt-in (a callback registered on some
        # OTHER class's pump) makes the class worth walking even with no
        # locks, threads, or selector of its own
        has_loop_marker = any(self._loop_marker(fn, m) == "on"
                              for fn in self.methods)
        self.thread_visible = bool(self.locks) or self.spawns_threads \
            or self.has_events or self.loop_class or has_loop_marker
        self.accesses: List[_Access] = []
        self.acquisitions: List[_LockEvent] = []
        self.blocking: List[_BlockingCall] = []
        self.loop_blocking: List[_BlockingCall] = []
        # Methods that lock manually (bare lock.acquire()/release()):
        # the walker's held-set is lexical (`with` blocks + held-method
        # conventions) and cannot track imperative acquire flow, so these
        # methods are exempt from guard checking and excluded from
        # inference rather than false-positived.  `with` is the
        # analyzable idiom (see README known limits).
        self.manual_lock_methods: Set[str] = set()
        if self.thread_visible:
            for fn in self.methods:
                if any(isinstance(n, ast.Call)
                       and isinstance(n.func, ast.Attribute)
                       and n.func.attr == "acquire"
                       and self.lock_of_expr(n.func.value) is not None
                       for n in ast.walk(fn)):
                    self.manual_lock_methods.add(fn.name)
                self._walk_method(m, fn)
        self.guards = self._build_guards()

    # -- member discovery ------------------------------------------------------

    def _collect_members(self, m: ModuleContext) -> None:
        non_locks: Set[str] = set()  # attrs locally assigned a non-lock
        class_body = set(map(id, self.cls.body))
        for node in _walk_class_scope(self.cls):
            if isinstance(node, ast.Call):
                q = m.imports.resolve(node.func)
                if q == THREAD_CTOR:
                    self.spawns_threads = True
                elif q is not None and q.startswith("selectors."):
                    # constructing a selector makes this an event-loop
                    # class: its methods default to on-loop (see
                    # on_loop()), and blocking calls there stall every
                    # connection the loop serves
                    self.loop_class = True
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if isinstance(value, ast.Call):
                q = m.imports.resolve(value.func)
            elif value is None:
                # bare typed declaration (`_lock: threading.RLock`, no
                # value — assigned by a base/harness): classify by the
                # annotation so the class stays thread-visible and the
                # member is a usable guard
                q = m.imports.resolve(node.annotation)
            else:
                q = None
            for target in targets:
                attr = _self_attr(target)
                if attr is None and isinstance(target, ast.Name) \
                        and id(node) in class_body:
                    # bare names are members only at CLASS level (a
                    # shared `_serial = RLock()`); method locals are not
                    attr = target.id
                if attr is None:
                    continue
                if q in LOCK_CTORS:
                    self.locks[attr] = LOCK_CTORS[q]
                else:
                    if q in EVENT_CTORS or q == CONDITION_CTOR:
                        self.has_events = True
                    if value is not None:
                        # only an attr VISIBLY ASSIGNED a non-lock may
                        # poison inherited-lock adoption; a value-less
                        # declaration assigns nothing
                        non_locks.add(attr)
        # Inherited locks: acquired here, constructed in a base class —
        # but never an attr this class visibly assigns a NON-lock (a file
        # handle or other context manager in a `with` must not poison
        # guard inference).
        for node in _walk_class_scope(self.cls):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and attr not in self.locks \
                            and attr not in non_locks:
                        self.locks[attr] = "inherited"

    def _collect_declarations(self, m: ModuleContext) -> None:
        for node in _walk_class_scope(self.cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            match = GUARDED_BY_RE.search(m.stmt_comment(node))
            if not match:
                continue
            lock = match.group(1)
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for target in targets:
                attr = _self_attr(target)
                if attr is None and isinstance(target, ast.Name):
                    attr = target.id
                if attr is None:
                    continue
                if lock not in self.locks:
                    self.bad_declarations.append((node, lock))
                else:
                    self.declared[attr] = lock

    # -- per-method walk -------------------------------------------------------

    def _holds_declaration(self, fn: ast.FunctionDef, m: ModuleContext
                           ) -> Optional[Set[str]]:
        """Raw lock names from a ``# holds-lock:`` annotation on the
        method header, or None when there is no annotation.  The comment
        may trail any header line or stand alone between the signature
        and the docstring (long signatures keep their type hints)."""
        first_body = fn.body[0].lineno if fn.body else fn.lineno + 1
        for line in range(fn.lineno, first_body):
            match = HOLDS_LOCK_RE.search(m.comments.get(line, ""))
            if match:
                return {n.strip() for n in match.group(1).split(",")
                        if n.strip()}
        return None

    def _loop_marker(self, fn: ast.FunctionDef, m: ModuleContext
                     ) -> Optional[str]:
        """'on' / 'off' from a ``# on-loop`` / ``# off-loop`` marker on
        the method header (same placement contract as '# holds-lock':
        trailing any header line or standing alone before the docstring),
        or None when unmarked.  off wins: 'off-loop' contains no
        'on-loop' match, but checking it first keeps the precedence
        explicit."""
        first_body = fn.body[0].lineno if fn.body else fn.lineno + 1
        for line in range(fn.lineno, first_body):
            comment = m.comments.get(line, "")
            if OFF_LOOP_RE.search(comment):
                return "off"
            if ON_LOOP_RE.search(comment):
                return "on"
        return None

    def on_loop(self, fn: ast.FunctionDef, m: ModuleContext) -> bool:
        """Does this method's body run on the event-loop thread?  An
        explicit marker always wins; otherwise every method of a
        selector-constructing class is presumed on-loop except
        constructors (they run on the spawning thread, before the loop
        exists)."""
        marker = self._loop_marker(fn, m)
        if marker is not None:
            return marker == "on"
        return self.loop_class and fn.name not in _CTOR_EXEMPT

    def held_for(self, fn: ast.FunctionDef, m: ModuleContext
                 ) -> FrozenSet[str]:
        names = self._holds_declaration(fn, m)
        if names is not None:
            return frozenset(n for n in names if n in self.locks)
        if fn.name.endswith("_locked"):
            return frozenset(self.locks)
        return frozenset()

    def lock_of_expr(self, node: ast.AST) -> Optional[str]:
        """Terminal lock name for ``self.X`` / ``<ClassName>.X`` / bare
        ``X`` when X is a known lock of this class."""
        attr = _self_attr(node)
        if attr is None and isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == self.name:
            attr = node.attr
        if attr is None and isinstance(node, ast.Name):
            attr = node.id
        return attr if attr is not None and attr in self.locks else None

    def _write_ids(self, fn: ast.FunctionDef) -> Set[int]:
        """ids of ``self.X`` Attribute nodes that are writes despite Load
        ctx: mutator-call receivers and subscript-store bases."""
        out: Set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in MUTATORS and \
                    _self_attr(node.func.value) is not None:
                out.add(id(node.func.value))
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    _self_attr(node.value) is not None:
                out.add(id(node.value))
        return out

    def _walk_method(self, m: ModuleContext, fn: ast.FunctionDef) -> None:
        write_ids = self._write_ids(fn)
        base_held = self.held_for(fn, m)
        on_loop = self.on_loop(fn, m)

        def visit(node: ast.AST, held: FrozenSet[str],
                  deferred: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # Deferred body: executes after the with-block exits,
                # possibly on another thread — locks are NOT held there.
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                for child in body:
                    visit(child, frozenset(), True)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                # `with a, b:` acquires sequentially: b's held-set
                # includes a, so opposite multi-item orders still cycle.
                acquired: List[str] = []
                for item in node.items:
                    lock = self.lock_of_expr(item.context_expr)
                    if lock is not None:
                        self.acquisitions.append(_LockEvent(
                            fn.name, lock, held | frozenset(acquired),
                            node))
                        acquired.append(lock)
                    else:
                        visit(item.context_expr, held, deferred)
                new_held = held | frozenset(acquired)
                for child in node.body:
                    visit(child, new_held, deferred)
                return
            if isinstance(node, ast.Call):
                self._classify_call(fn, node, held,
                                    on_loop=on_loop and not deferred)
            attr = _self_attr(node)
            if attr is not None and attr not in self.locks:
                write = isinstance(node.ctx, (ast.Store, ast.Del)) \
                    or id(node) in write_ids
                self.accesses.append(_Access(
                    fn.name, attr, write, held, node, deferred))
            for child in ast.iter_child_nodes(node):
                visit(child, held, deferred)

        for stmt in fn.body:
            visit(stmt, base_held, False)

    def _classify_call(self, fn: ast.FunctionDef, node: ast.Call,
                       held: FrozenSet[str], on_loop: bool = False
                       ) -> None:
        func = node.func
        name = None
        if isinstance(func, ast.Attribute):
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        if name is None:
            return
        if name == "acquire" and isinstance(func, ast.Attribute):
            lock = self.lock_of_expr(func.value)
            self.acquisitions.append(_LockEvent(
                fn.name, lock if lock is not None else "<unknown>",
                held, node))
            return
        if name == "wait" and isinstance(func, ast.Attribute):
            recv = _terminal_name(func.value)
            if recv in self._module_events:
                if held:
                    self.blocking.append(_BlockingCall(
                        fn.name, f"{recv}.wait", held, node))
                if on_loop:
                    self.loop_blocking.append(_BlockingCall(
                        fn.name, f"{recv}.wait", held, node))
            return
        if name in BLOCKING_CALLS:
            if held:
                self.blocking.append(_BlockingCall(
                    fn.name, name, held, node))
            if on_loop and name not in LOOP_EXEMPT_CALLS:
                self.loop_blocking.append(_BlockingCall(
                    fn.name, name, held, node))

    # -- guard relation --------------------------------------------------------

    def _build_guards(self) -> Dict[str, str]:
        guards = dict(self.declared)
        writes: Dict[str, List[_Access]] = {}
        for a in self.accesses:
            if a.write and a.method not in _CTOR_EXEMPT \
                    and a.method not in self.manual_lock_methods \
                    and a.attr not in guards:
                writes.setdefault(a.attr, []).append(a)
        for attr, ws in writes.items():
            if all(w.held for w in ws):
                common = frozenset.intersection(*(w.held for w in ws))
                if len(common) == 1:
                    # Exactly one common lock: unambiguous adoption.  More
                    # than one (e.g. writes only in `_locked` methods of a
                    # two-lock class, where ALL locks are assumed held)
                    # would make the guard a guess — flagging reads
                    # against the wrong lock; such attrs need an explicit
                    # declaration to be enforced.
                    guards[attr] = next(iter(common))
        return guards


def class_models(m: ModuleContext) -> List[_ClassModel]:
    """Thread-visible class models for a module, built once per context:
    five rules consume the identical model, so it is memoized on the
    ModuleContext (same pattern as its lazy ``comments``)."""
    cached = getattr(m, "_race_models", None)
    if cached is None:
        cached = [
            model for node in ast.walk(m.tree)
            if isinstance(node, ast.ClassDef)
            for model in [_ClassModel(m, node)]
            if model.thread_visible
        ]
        m._race_models = cached
    return cached


# -- rules --------------------------------------------------------------------


@register
class GuardedAccessRule(Rule):
    name = "FL-RACE-GUARD"
    severity = "error"
    scope = ("fluidframework_tpu/",)
    description = (
        "read/write of a guarded attribute outside its lock in a "
        "thread-visible class; guards come from '# guarded-by: <lock>' "
        "declarations or all-writes-under-one-lock inference"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for model in class_models(m):
            for node, lock in model.bad_declarations:
                yield m.finding(
                    self, node,
                    f"'# guarded-by: {lock}' in class {model.name} names "
                    "no known lock attribute of that class — fix the "
                    "annotation or construct the lock in this class",
                )
            for fn, lock in model.bad_holds:
                yield m.finding(
                    self, fn,
                    f"'# holds-lock: {lock}' {_owner_phrase(fn.name)} of "
                    f"{model.name} names no known lock attribute of that "
                    "class — the annotation exempts nothing and guard "
                    "inference for the attributes it writes is silently "
                    "declined; fix the name or construct the lock in "
                    "this class",
                )
            for a in model.accesses:
                if a.method in _CTOR_EXEMPT or \
                        a.method in model.manual_lock_methods:
                    continue
                lock = model.guards.get(a.attr)
                if lock is None or lock in a.held:
                    continue
                kind = "write to" if a.write else "read of"
                where = "deferred callback in " if a.deferred else ""
                yield m.finding(
                    self, a.node,
                    f"{kind} '{a.attr}' (guarded by '{lock}') outside the "
                    f"lock in {where}{a.method}() of {model.name}; take "
                    f"'with self.{lock}:' around the access or mark the "
                    "method as lock-held ('# holds-lock', '_locked' "
                    "suffix)",
                )


@register
class BlockingUnderLockRule(Rule):
    name = "FL-RACE-BLOCKING"
    severity = "error"
    scope = ("fluidframework_tpu/",)
    description = (
        "blocking operation (nested acquire, Event.wait, RPC/fold/pack "
        "blocklist call) while holding a lock — stalls every thread "
        "contending for it — or inside an on-loop event-loop callback, "
        "where it stalls every connection the loop serves"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for model in class_models(m):
            for acq in model.acquisitions:
                if not acq.held_before:
                    continue
                if acq.lock in acq.held_before and \
                        model.locks.get(acq.lock) in ("rlock", "inherited"):
                    continue  # re-entrant re-acquire: the ORDER rule's
                    # self-cycle check covers non-reentrant locks
                if isinstance(acq.node, ast.Call):
                    held = ", ".join(sorted(acq.held_before))
                    # ".acquire()" (dot-prefixed) so the baseline hygiene
                    # check reads it as an API name, not a function key.
                    yield m.finding(
                        self, acq.node,
                        f"bare .acquire() call on '{acq.lock}' in "
                        f"{acq.method}() of {model.name} while holding "
                        f"'{held}'; nested blocking acquisition — "
                        "restructure to one critical section or a fixed "
                        "lock order with 'with'",
                    )
            flagged: Set[int] = set()
            for b in model.blocking:
                flagged.add(id(b.node))
                held = ", ".join(sorted(b.held))
                yield m.finding(
                    self, b.node,
                    f"blocking call '{b.name}' in {b.method}() of "
                    f"{model.name} while holding '{held}'; move the slow "
                    "work outside the critical section (copy state out, "
                    "drop the lock, then block)",
                )
            for b in model.loop_blocking:
                if id(b.node) in flagged:
                    continue  # under-lock finding already covers it
                yield m.finding(
                    self, b.node,
                    f"blocking call '{b.name}' in on-loop method "
                    f"{b.method}() of {model.name} — a blocking "
                    "event-loop callback stalls EVERY connection on the "
                    "loop; hand the work to a worker thread and write "
                    "the reply back cross-thread, or mark the method "
                    "'# off-loop' if it never runs on the loop thread",
                )


@register
class LockOrderRule(Rule):
    name = "FL-RACE-ORDER"
    severity = "error"
    scope = ("fluidframework_tpu/",)
    description = (
        "inconsistent lock-acquisition order across a class's methods "
        "(cycle in the per-class lock graph) or self-acquisition of a "
        "non-reentrant lock — deadlock candidates"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for model in class_models(m):
            edges: Dict[str, Set[str]] = {}
            sites: Dict[Tuple[str, str], _LockEvent] = {}
            for acq in model.acquisitions:
                if acq.lock == "<unknown>":
                    continue
                if acq.lock in acq.held_before:
                    if model.locks.get(acq.lock) == "lock":
                        yield m.finding(
                            self, acq.node,
                            f"re-acquiring non-reentrant Lock "
                            f"'{acq.lock}' already held in {acq.method}() "
                            f"of {model.name} — guaranteed self-deadlock; "
                            "use an RLock or split the critical section",
                        )
                    continue
                for held in acq.held_before:
                    edges.setdefault(held, set()).add(acq.lock)
                    sites.setdefault((held, acq.lock), acq)
            for cycle in _find_cycles(edges):
                first = sites[(cycle[0], cycle[1])]
                methods = sorted({sites[(cycle[i], cycle[i + 1])].method
                                  for i in range(len(cycle) - 1)})
                yield m.finding(
                    self, first.node,
                    f"lock-order cycle in {model.name}: "
                    f"{' -> '.join(cycle)} (acquired in "
                    f"{', '.join(methods)}) — two threads taking the "
                    "locks in opposite order deadlock; pick one global "
                    "order",
                )


@register
class MutateDuringIterationRule(Rule):
    name = "FL-RACE-MUTITER"
    severity = "error"
    scope = ("fluidframework_tpu/",)
    description = (
        "iterating a guarded dict/set attribute while mutating it in the "
        "loop body — RuntimeError under concurrency (and alone); iterate "
        "a snapshot (list(...)) and mutate after"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for model in class_models(m):
            for fn in model.methods:
                write_ids = model._write_ids(fn)
                for node in ast.walk(fn):
                    if not isinstance(node, (ast.For, ast.AsyncFor)):
                        continue
                    attr = self._iterated_guarded_attr(model, node.iter)
                    if attr is None:
                        continue
                    if self._body_mutates(fn, node, attr, write_ids):
                        yield m.finding(
                            self, node,
                            f"iterating 'self.{attr}' while mutating it "
                            f"in the loop body in {fn.name}() of "
                            f"{model.name}; snapshot first "
                            f"(list(self.{attr})) or collect keys and "
                            "mutate after the loop",
                        )

    @staticmethod
    def _iterated_guarded_attr(model, it: ast.AST) -> Optional[str]:
        if isinstance(it, ast.Call):
            func = it.func
            if isinstance(func, ast.Name):
                return None  # list(...)/sorted(...) snapshot — safe
            if isinstance(func, ast.Attribute) and \
                    func.attr in ("keys", "values", "items"):
                it = func.value
            else:
                return None
        attr = _self_attr(it)
        return attr if attr is not None and attr in model.guards else None

    @staticmethod
    def _body_mutates(fn, loop, attr: str, write_ids: Set[int]) -> bool:
        for node in _walk_pruned(loop):
            if node is loop.iter:
                continue
            a = _self_attr(node)
            if a == attr and (isinstance(node.ctx, (ast.Store, ast.Del))
                              or id(node) in write_ids):
                return True
        return False


@register
class CheckThenActRule(Rule):
    name = "FL-RACE-CHECKACT"
    severity = "warning"
    scope = ("fluidframework_tpu/",)
    description = (
        "guarded state read under a lock and mutated under a later, "
        "separate acquisition of the same lock in one method — the "
        "decision may be stale by the time it is applied"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for model in class_models(m):
            for fn in model.methods:
                if fn.name in _CTOR_EXEMPT or \
                        model.held_for(fn, m):
                    continue
                yield from self._check_method(m, model, fn)

    def _check_method(self, m, model, fn) -> Iterator[Finding]:
        blocks = self._lock_blocks(m, model, fn)
        seen_reads: Set[Tuple[str, str]] = set()  # (lock, attr)
        reported: Set[Tuple[str, str]] = set()
        for lock, reads, writes, node in blocks:
            for attr in writes:
                key = (lock, attr)
                if key in seen_reads and key not in reported:
                    reported.add(key)
                    yield m.finding(
                        self, node,
                        f"check-then-act on '{attr}' in {fn.name}() of "
                        f"{model.name}: read under '{lock}', mutated "
                        "under a later separate acquisition — another "
                        "thread can change it in between; merge into one "
                        "critical section or re-validate before mutating",
                    )
            for attr in reads:
                seen_reads.add((lock, attr))

    def _lock_blocks(self, m, model, fn):
        """(lock, guarded-reads, guarded-writes, node) per OUTERMOST
        with-block on each lock, in source order, nested callables
        excluded.  A nested re-acquire of an already-held lock is the
        same critical section (an RLock never releases in between), not
        a separate acquisition."""
        write_ids = model._write_ids(fn)
        blocks = []

        def visit(node, held):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            acquired = []
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = model.lock_of_expr(item.context_expr)
                    if lock is None or lock in held:
                        continue
                    acquired.append(lock)
                    reads: Set[str] = set()
                    writes: Set[str] = set()
                    for sub in _walk_pruned(node):
                        attr = _self_attr(sub)
                        if attr is None or \
                                model.guards.get(attr) != lock:
                            continue
                        if isinstance(sub.ctx, (ast.Store, ast.Del)) \
                                or id(sub) in write_ids:
                            writes.add(attr)
                        else:
                            reads.add(attr)
                    blocks.append((lock, reads, writes, node))
            for child in ast.iter_child_nodes(node):
                visit(child, held | set(acquired))

        for stmt in fn.body:
            visit(stmt, model.held_for(fn, m))
        return blocks


@register
class UnboundedWaitRule(Rule):
    name = "FL-RACE-WAITFOREVER"
    severity = "error"
    scope = SERVING_SCOPE
    description = (
        "Event.wait()/Thread.join() with no timeout on a serving path — "
        "a crashed peer thread hangs the waiter forever; pass a bounded "
        "timeout and handle the expiry"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        events, threads, conditions = _module_waitables(m)
        for fn_name, node in _calls_with_owner(m.tree):
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if node.args or any(kw.arg == "timeout" for kw in node.keywords):
                continue
            recv = _terminal_name(func.value)
            where = _owner_phrase(fn_name)
            if func.attr == "wait" and recv in (events | conditions):
                yield m.finding(
                    self, node,
                    f"{recv}.wait() with no timeout {where} on a "
                    "serving path; a crashed setter/notifier hangs this "
                    "thread forever — wait(timeout) and handle the "
                    "expiry",
                )
            elif func.attr == "join" and recv in threads:
                yield m.finding(
                    self, node,
                    f"{recv}.join() with no timeout {where} on a "
                    "serving path; a wedged thread hangs shutdown — "
                    "join(timeout) and surface the leak",
                )


# -- shared helpers -----------------------------------------------------------


def _walk_class_scope(cls: ast.ClassDef) -> Iterator[ast.AST]:
    """Walk a class without descending into nested classes: a nested
    class's locks, members, and '# guarded-by' declarations belong to
    ITS model (class_models builds one per ClassDef, nested included),
    and adopting them here would flag the enclosing class's same-named
    attributes against a guard it does not have."""
    stack: List[ast.AST] = [cls]
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, ast.ClassDef) and cur is not cls:
            continue
        stack.extend(ast.iter_child_nodes(cur))


def _walk_pruned(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a subtree without descending into nested function/lambda
    bodies — those run deferred, outside the enclosing critical section
    (the same boundary the access walker draws)."""
    stack: List[ast.AST] = [node]
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)) and cur is not node:
            continue
        stack.extend(ast.iter_child_nodes(cur))


def _owner_phrase(fn_name: str) -> str:
    """Render the owning scope for a message; '<module>()' would trip
    the baseline function-hygiene check (no such def exists)."""
    return "at module scope" if fn_name == "<module>" else f"in {fn_name}()"


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _module_waitables(m: ModuleContext
                      ) -> Tuple[Set[str], Set[str], Set[str]]:
    """Terminal names bound (anywhere in the module) to Event, Thread,
    and Condition constructors: ``(events, threads, conditions)``."""
    events: Set[str] = set()
    threads: Set[str] = set()
    conditions: Set[str] = set()
    for node in ast.walk(m.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)) or \
                not isinstance(node.value, ast.Call):
            continue
        q = m.imports.resolve(node.value.func)
        if q is None:
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for target in targets:
            name = _terminal_name(target)
            if name is None:
                continue
            if q in EVENT_CTORS:
                events.add(name)
            elif q == CONDITION_CTOR:
                conditions.add(name)
            elif q == THREAD_CTOR:
                threads.add(name)
    return events, threads, conditions


def _calls_with_owner(tree: ast.Module) -> Iterator[Tuple[str, ast.Call]]:
    """(owning function name, call node) for every call, innermost owner
    wins; module-level calls report '<module>'."""

    def visit(node: ast.AST, owner: str) -> Iterator[Tuple[str, ast.Call]]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            owner = node.name
        if isinstance(node, ast.Call):
            yield owner, node
        for child in ast.iter_child_nodes(node):
            yield from visit(child, owner)

    yield from visit(tree, "<module>")


def _find_cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Each distinct lock cycle once, as [a, b, ..., a], smallest start
    first (deterministic output for stable suppression keys)."""
    cycles: List[List[str]] = []
    seen: Set[FrozenSet[str]] = set()
    nodes = sorted(set(edges) | {n for vs in edges.values() for n in vs})

    def dfs(start: str, current: str, path: List[str],
            on_path: Set[str]) -> None:
        for nxt in sorted(edges.get(current, ())):
            if nxt == start:
                members = frozenset(path)
                if members not in seen:
                    seen.add(members)
                    cycles.append(path + [start])
            elif nxt not in on_path and nxt > start:
                # only walk nodes > start: each cycle is discovered from
                # its smallest member exactly once
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for n in nodes:
        dfs(n, n, [n], {n})
    return cycles
