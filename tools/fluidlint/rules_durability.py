"""fluiddur — durability-ordering & crash-consistency rules.

The serving tier's durability story is a set of ORDERINGS: a temp file
is flushed and fsynced before the rename that publishes it; nothing
externally visible (an ack, a broadcast) happens before the durable
write that commits the operation; in-memory state that shadows durable
state (sequence counters, dedup floors) is unwound when the durable
write fails; a single logical record is one ``.write()`` between fsync
points.  Every one of those orderings was previously enforced only by
the crash-sweep tests someone remembered to write — ALICE-style
application-level crash-consistency checking shows these bugs are
systematic and statically findable, so this family makes the orderings
checked invariants.

Annotation conventions (trailing comments, like ``guarded-by``):

``# commit-point: <label>``
    On the statement whose durable write commits an operation.  Calls
    with externally-visible effects (broadcast/ack/notify/...) reachable
    on a path BEFORE the commit point are FL-DUR-COMMIT findings — a
    broadcast cannot be un-broadcast when the write fails.

``# durable-shadow: <note>``
    On an attribute assignment declaring in-memory state that shadows
    durable state.  FL-DUR-UNWIND tracks mutations of these attributes.

``# unwinds: a, b``
    On a fallible durable-write call that is reached after shadow
    mutations: the enclosing ``try``'s handlers must restore every named
    attribute (directly, through a local alias, or through one same-class
    method call — the sequencer's ``_drop``-style restore).

``# durable-handle: single-record``
    On the assignment binding a durable file handle attribute: within
    any one method, at most one ``.write()`` call site may touch the
    handle between fsync points (FL-DUR-TORN).

Known limits (documented in the README): file handles reached through
local aliases are invisible to TORN; a write and its fsync split across
two functions (other than a one-level ``self.flush()``-style helper) are
invisible to RENAME/TORN; shadow mutations hidden inside callee methods
are invisible to UNWIND (the caller's annotation is the contract).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from .core import (Finding, ModuleContext, ProjectContext, ProjectRule,
                   Rule, register)
from .rules_concurrency import _owner_phrase, _walk_pruned as _fn_walk
from .rules_lifecycle import _dotted, _exit_paths_for, _functions

COMMIT_RE = re.compile(r"commit-point:\s*(\S.*)")
SHADOW_RE = re.compile(r"durable-shadow\b")
UNWINDS_RE = re.compile(r"unwinds:\s*([A-Za-z_][\w, ]*)")
HANDLE_RE = re.compile(r"durable-handle:\s*single-record")

#: terminal call names whose effect escapes the process (or the caller's
#: ability to roll back): flagged before a commit point.
VISIBLE_EFFECTS = frozenset({
    "broadcast", "deliver", "publish", "notify", "notify_all",
    "_notify_commit", "ack", "nack", "respond", "reply", "emit",
    "send", "sendall", "send_frame", "write_frame",
    "set_result", "set_exception",
})

#: method names that mutate their receiver in place.
MUTATORS = frozenset({
    "append", "extend", "add", "update", "insert", "setdefault",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
})


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _stmts(fn: ast.AST) -> Iterator[ast.stmt]:
    """Every statement of ``fn`` in lexical order, nested defs pruned."""
    out = [n for n in _fn_walk(fn) if isinstance(n, ast.stmt) and n is not fn]
    out.sort(key=lambda n: (n.lineno, n.col_offset))
    return iter(out)


def _calls(fn: ast.AST) -> List[ast.Call]:
    out = [n for n in _fn_walk(fn) if isinstance(n, ast.Call)]
    out.sort(key=lambda n: (n.lineno, n.col_offset))
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    """'X' for a ``self.X`` attribute expression, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _target_attr(target: ast.AST) -> Optional[str]:
    """'X' when an assignment target is ``self.X`` or ``self.X[...]``."""
    if isinstance(target, ast.Subscript):
        target = target.value
    return _self_attr(target)


def _classes(tree: ast.Module) -> Iterator[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node


def _methods(cls: ast.ClassDef) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(cls):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# -- FL-DUR-RENAME ------------------------------------------------------------


def _tmpish(text: str) -> bool:
    low = text.lower()
    return "tmp" in low or ".compact" in low or "temp" in low


@register
class DurRenameRule(Rule):
    """Temp-write → publish must fsync the artifact before the rename,
    and the rename must be ``os.replace`` (atomic-overwrite)."""

    name = "FL-DUR-RENAME"
    severity = "error"
    description = ("temp-write→publish paths must flush()+os.fsync() the "
                   "artifact before an os.replace (never os.rename)")

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for fn in _functions(m.tree):
            yield from self._check_fn(m, fn)

    def _check_fn(self, m: ModuleContext, fn) -> Iterator[Finding]:
        calls = _calls(fn)
        resolved = [(c, m.imports.resolve(c.func)) for c in calls]
        fsync_lines = [c.lineno for c, r in resolved if r == "os.fsync"]
        # local Name -> assigned-expression text (for tmp-ness lookup)
        assigns: Dict[str, str] = {}
        for st in _stmts(fn):
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                assigns[st.targets[0].id] = ast.unparse(st.value)
        for call, qual in resolved:
            if qual == "os.rename":
                yield m.finding(self, call, (
                    f"os.rename() {_owner_phrase(fn.name)}: use os.replace() "
                    f"— rename is not atomic-overwrite on all platforms"))
            if qual != "os.replace" or not call.args:
                continue
            src = call.args[0]
            src_text = ast.unparse(src)
            tmp = _tmpish(src_text)
            if isinstance(src, ast.Name) and not tmp:
                tmp = _tmpish(assigns.get(src.id, ""))
            if not tmp:
                continue
            if not any(line < call.lineno for line in fsync_lines):
                yield m.finding(self, call, (
                    f"os.replace() {_owner_phrase(fn.name)} publishes temp "
                    f"artifact '{src_text}' with no os.fsync() before the "
                    f"rename — a crash can publish an empty or torn file"))
        # fsync on a buffered handle written in this function must be
        # preceded by .flush() — fsync of an unflushed handle syncs
        # nothing.
        writes_by_recv: Dict[str, List[int]] = {}
        flush_by_recv: Dict[str, List[int]] = {}
        for call in calls:
            if isinstance(call.func, ast.Attribute):
                recv = _dotted(call.func.value)
                if recv is None:
                    continue
                if call.func.attr == "write":
                    writes_by_recv.setdefault(recv, []).append(call.lineno)
                elif call.func.attr == "flush":
                    flush_by_recv.setdefault(recv, []).append(call.lineno)
        for call, qual in resolved:
            if qual != "os.fsync" or not call.args:
                continue
            arg = call.args[0]
            if not (isinstance(arg, ast.Call)
                    and isinstance(arg.func, ast.Attribute)
                    and arg.func.attr == "fileno"):
                continue
            recv = _dotted(arg.func.value)
            if recv is None or recv not in writes_by_recv:
                continue
            if not any(line <= call.lineno
                       for line in flush_by_recv.get(recv, [])):
                yield m.finding(self, call, (
                    f"os.fsync() on '{recv}' {_owner_phrase(fn.name)} "
                    f"without a preceding .flush() — buffered bytes are "
                    f"not on disk when the fsync returns"))


# -- FL-DUR-COMMIT ------------------------------------------------------------


@register
class DurCommitRule(Rule):
    """Nothing externally visible before the annotated commit point."""

    name = "FL-DUR-COMMIT"
    severity = "error"
    description = ("no ack/broadcast/notify reachable on a path before the "
                   "'# commit-point:' durable write that commits the op")

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for fn in _functions(m.tree):
            yield from self._check_fn(m, fn)

    def _check_fn(self, m: ModuleContext, fn) -> Iterator[Finding]:
        commit_calls: List[ast.Call] = []
        labels: Dict[int, str] = {}
        for st in _stmts(fn):
            match = COMMIT_RE.search(m.stmt_comment(st))
            if not match:
                continue
            in_stmt = [n for n in ast.walk(st) if isinstance(n, ast.Call)]
            if not in_stmt:
                yield m.finding(self, st, (
                    f"'# commit-point:' annotation {_owner_phrase(fn.name)} "
                    f"on a statement with no call — the commit point must "
                    f"be the durable write itself"))
                continue
            commit_calls.extend(in_stmt)
            for c in in_stmt:
                labels[id(c)] = match.group(1).strip()
        if not commit_calls:
            return
        commit_ids = {id(c) for c in commit_calls}
        paths = _exit_paths_for(m, fn)
        flagged: Set[int] = set()
        if paths is None:
            # budget exceeded: lexical fallback
            first = min(c.lineno for c in commit_calls)
            for call in _calls(fn):
                name = _terminal(call.func)
                if name in VISIBLE_EFFECTS and call.lineno < first \
                        and id(call) not in flagged:
                    flagged.add(id(call))
                    yield m.finding(self, call, (
                        f"'{name}()' {_owner_phrase(fn.name)} precedes the "
                        f"commit point — visible before the op is durable"))
            return
        for path in paths:
            idx = next((i for i, ev in enumerate(path.events)
                        if id(ev.node) in commit_ids), None)
            if idx is None:
                continue
            label = labels.get(id(path.events[idx].node), "")
            for ev in path.events[:idx]:
                if ev.kind != "call" or id(ev.node) in commit_ids:
                    continue
                name = _terminal(ev.node.func) \
                    if isinstance(ev.node, ast.Call) else None
                if name in VISIBLE_EFFECTS and id(ev.node) not in flagged:
                    flagged.add(id(ev.node))
                    yield m.finding(self, ev.node, (
                        f"'{name}()' {_owner_phrase(fn.name)} is reachable "
                        f"before commit point '{label}' — the effect is "
                        f"visible before the op is durable"))


# -- FL-DUR-UNWIND ------------------------------------------------------------


def _method_restores(method, shadow: Set[str]) -> Set[str]:
    """Shadow attrs a method restores lexically (assign / augassign /
    subscript-assign / mutator call on ``self.X``)."""
    out: Set[str] = set()
    for node in _fn_walk(method):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _target_attr(t)
                if attr in shadow:
                    out.add(attr)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATORS:
            attr = _self_attr(node.func.value)
            if attr in shadow:
                out.add(attr)
    return out


@register
class DurUnwindRule(Rule):
    """Shadow state mutated before a fallible durable write must be
    restored by the write's exception handlers (the un-stamp
    discipline, generalized)."""

    name = "FL-DUR-UNWIND"
    severity = "error"
    description = ("'# durable-shadow:' state mutated before a durable "
                   "write needs an '# unwinds:' pairing whose try handlers "
                   "restore it on every exception exit")

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for cls in _classes(m.tree):
            yield from self._check_class(m, cls)

    def _check_class(self, m: ModuleContext, cls) -> Iterator[Finding]:
        shadow: Set[str] = set()
        for method in _methods(cls):
            for st in _stmts(method):
                if not isinstance(st, (ast.Assign, ast.AnnAssign)):
                    continue
                if not SHADOW_RE.search(m.stmt_comment(st)):
                    continue
                targets = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                for t in targets:
                    attr = _target_attr(t)
                    if attr:
                        shadow.add(attr)
        methods = list(_methods(cls))
        restores_of: Dict[str, Set[str]] = {
            meth.name: _method_restores(meth, shadow) for meth in methods}
        for method in methods:
            yield from self._check_method(m, cls, method, shadow,
                                          restores_of)

    def _aliases(self, method, shadow: Set[str]) -> Dict[str, str]:
        """local name -> shadow attr it aliases (``log = self._docs...``)."""
        out: Dict[str, str] = {}
        for st in _fn_walk(method):
            if not (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)):
                continue
            for node in ast.walk(st.value):
                attr = _self_attr(node)
                if attr in shadow:
                    out[st.targets[0].id] = attr
                    break
        return out

    def _mutations(self, method, shadow: Set[str],
                   aliases: Dict[str, str]) -> List[Tuple[int, str, ast.AST]]:
        """(line, attr, node) for every lexical mutation of shadow state
        in ``method``, through ``self.X`` or a local alias."""
        def _hit(target: ast.AST) -> Optional[str]:
            attr = _target_attr(target)
            if attr in shadow:
                return attr
            # ``log[-1] = ...`` through a local alias mutates the attr;
            # rebinding the alias name itself does not.
            if isinstance(target, ast.Subscript) \
                    and isinstance(target.value, ast.Name):
                return aliases.get(target.value.id)
            return None

        out: List[Tuple[int, str, ast.AST]] = []
        for node in _fn_walk(method):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _hit(t)
                    if attr:
                        out.append((node.lineno, attr, node))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATORS:
                recv = node.func.value
                attr = _self_attr(recv)
                if attr is None and isinstance(recv, ast.Name):
                    attr = aliases.get(recv.id)
                if attr in shadow:
                    out.append((node.lineno, attr, node))
        out.sort(key=lambda t: t[0])
        return out

    def _handler_restores(self, handler, shadow: Set[str],
                          aliases: Dict[str, str],
                          restores_of: Dict[str, Set[str]]) -> Set[str]:
        out: Set[str] = set()
        for node in _fn_walk(handler):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = _target_attr(t)
                    if attr is None and isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name):
                        attr = aliases.get(t.value.id)
                    if attr in shadow:
                        out.add(attr)
            elif isinstance(node, ast.Call):
                if isinstance(node.func, ast.Attribute):
                    recv = node.func.value
                    if node.func.attr in MUTATORS:
                        attr = _self_attr(recv)
                        if attr is None and isinstance(recv, ast.Name):
                            attr = aliases.get(recv.id)
                        if attr in shadow:
                            out.add(attr)
                    # one-level interprocedural: self._drop(...)-style
                    # same-class restore helpers
                    if isinstance(recv, ast.Name) and recv.id == "self":
                        out |= restores_of.get(node.func.attr, set())
        return out

    def _check_method(self, m: ModuleContext, cls, method,
                      shadow: Set[str],
                      restores_of: Dict[str, Set[str]]) -> Iterator[Finding]:
        aliases = self._aliases(method, shadow)
        mutations = self._mutations(method, shadow, aliases)
        # mutations inside except handlers ARE the restores; don't count
        # them as pre-commit advances.
        handler_lines: Set[int] = set()
        for node in _fn_walk(method):
            if isinstance(node, ast.ExceptHandler):
                for sub in ast.walk(node):
                    if hasattr(sub, "lineno"):
                        handler_lines.add(sub.lineno)
        mutations = [mu for mu in mutations if mu[0] not in handler_lines]

        tries = [n for n in _fn_walk(method) if isinstance(n, ast.Try)]

        def enclosing_try(call_line: int) -> Optional[ast.Try]:
            best = None
            for t in tries:
                if t.lineno <= call_line <= (t.end_lineno or t.lineno) \
                        and t.handlers:
                    if best is None or t.lineno > best.lineno:
                        best = t
            return best

        for st in _stmts(method):
            comment = m.stmt_comment(st)
            unwinds = UNWINDS_RE.search(comment)
            is_commit = COMMIT_RE.search(comment) is not None
            if not unwinds and not is_commit:
                continue
            names = [n.strip() for n in unwinds.group(1).split(",")
                     if n.strip()] if unwinds else []
            for name in names:
                if name not in shadow:
                    yield m.finding(self, st, (
                        f"'# unwinds: {name}' {_owner_phrase(method.name)} "
                        f"names an attribute not declared "
                        f"'# durable-shadow:' on {cls.name}"))
            names = [n for n in names if n in shadow]
            pre = {attr for line, attr, _ in mutations if line < st.lineno}
            if not names:
                # bare commit point: any shadow advance before it is an
                # unpaired mutation
                if is_commit and pre:
                    yield m.finding(self, st, (
                        f"shadow state {sorted(pre)} mutated before the "
                        f"commit point {_owner_phrase(method.name)} with "
                        f"no '# unwinds:' pairing — a failed durable "
                        f"write leaves memory ahead of disk"))
                continue
            uncovered = pre - set(names)
            if is_commit and uncovered:
                yield m.finding(self, st, (
                    f"shadow state {sorted(uncovered)} mutated before the "
                    f"commit point {_owner_phrase(method.name)} is not in "
                    f"its '# unwinds:' list"))
            t = enclosing_try(st.lineno)
            if t is None:
                yield m.finding(self, st, (
                    f"durable write annotated '# unwinds: "
                    f"{', '.join(names)}' {_owner_phrase(method.name)} is "
                    f"not inside a try with exception handlers — nothing "
                    f"restores the shadow state on failure"))
                continue
            restored: Set[str] = set()
            for handler in t.handlers:
                restored |= self._handler_restores(handler, shadow,
                                                  aliases, restores_of)
            for name in names:
                if name not in restored:
                    yield m.finding(self, st, (
                        f"exception handlers around the durable write "
                        f"{_owner_phrase(method.name)} do not restore "
                        f"'# unwinds:' attribute '{name}'"))


# -- FL-DUR-TORN --------------------------------------------------------------


@register
class DurTornRule(Rule):
    """At most one ``.write()`` call site on a single-record durable
    handle between fsync points (torn-write exposure)."""

    name = "FL-DUR-TORN"
    severity = "error"
    description = ("more than one .write() on a '# durable-handle: "
                   "single-record' file handle between fsync points is "
                   "torn-write exposure")

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for cls in _classes(m.tree):
            yield from self._check_class(m, cls)

    def _check_class(self, m: ModuleContext, cls) -> Iterator[Finding]:
        handles: Set[str] = set()
        for method in _methods(cls):
            for st in _stmts(method):
                if not isinstance(st, (ast.Assign, ast.AnnAssign)):
                    continue
                if not HANDLE_RE.search(m.stmt_comment(st)):
                    continue
                targets = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                for t in targets:
                    attr = _target_attr(t)
                    if attr:
                        handles.add(attr)
        if not handles:
            return
        methods = list(_methods(cls))
        # same-class methods that fsync a handle count as fsync points
        # (OpLog.flush() style); one level only.
        fsyncers: Dict[str, Set[str]] = {h: set() for h in handles}
        for method in methods:
            for call in _calls(method):
                if not (m.imports.resolve(call.func) == "os.fsync"
                        and call.args):
                    continue
                arg = call.args[0]
                if isinstance(arg, ast.Call) \
                        and isinstance(arg.func, ast.Attribute) \
                        and arg.func.attr == "fileno":
                    attr = _self_attr(arg.func.value)
                    if attr in handles:
                        fsyncers[attr].add(method.name)
        for method in methods:
            yield from self._check_method(m, method, handles, fsyncers)

    def _check_method(self, m: ModuleContext, method, handles: Set[str],
                      fsyncers: Dict[str, Set[str]]) -> Iterator[Finding]:
        pending: Dict[str, Optional[ast.Call]] = {h: None for h in handles}
        for call in _calls(method):
            if not isinstance(call.func, ast.Attribute):
                continue
            recv_attr = _self_attr(call.func.value)
            if call.func.attr == "write" and recv_attr in handles:
                prev = pending[recv_attr]
                if prev is not None and prev is not call:
                    yield m.finding(self, call, (
                        f"second .write() on single-record handle "
                        f"'self.{recv_attr}' {_owner_phrase(method.name)} "
                        f"before an fsync point — a crash between the "
                        f"writes leaves a torn record"))
                pending[recv_attr] = call
                continue
            # fsync points: os.fsync(self.X.fileno()) or a same-class
            # helper known to fsync the handle (self.flush()).
            if m.imports.resolve(call.func) == "os.fsync" and call.args:
                arg = call.args[0]
                if isinstance(arg, ast.Call) \
                        and isinstance(arg.func, ast.Attribute) \
                        and arg.func.attr == "fileno":
                    attr = _self_attr(arg.func.value)
                    if attr in handles:
                        pending[attr] = None
                continue
            if isinstance(call.func.value, ast.Name) \
                    and call.func.value.id == "self":
                for h in handles:
                    if call.func.attr in fsyncers[h]:
                        pending[h] = None


# -- FL-DUR-SEAM --------------------------------------------------------------


FAULTS_MODULE = "fluidframework_tpu/testing/faults.py"


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _registered_sites(tree: ast.Module) -> Tuple[Dict[str, int], Dict[str, int]]:
    """(SITES key -> line, SCHEDULED_SITES entry -> line)."""
    sites: Dict[str, int] = {}
    scheduled: Dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        value = node.value
        if "SITES" in names and isinstance(value, ast.Dict):
            for key in value.keys:
                lit = _const_str(key)
                if lit is not None:
                    sites[lit] = key.lineno
        elif "SCHEDULED_SITES" in names \
                and isinstance(value, (ast.Tuple, ast.List)):
            for el in value.elts:
                lit = _const_str(el)
                if lit is not None:
                    scheduled[lit] = el.lineno
    return sites, scheduled


@register
class DurSeamRule(ProjectRule):
    """Fault-seam registry drift: every registered site is armed
    somewhere, every armed site is registered."""

    name = "FL-DUR-SEAM"
    severity = "error"
    description = ("every testing/faults.py SITES entry must be armed by a "
                   "fire()/due()/schedule literal somewhere in the package, "
                   "and every fired site literal must be registered")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        tree = project.parse(FAULTS_MODULE)
        if tree is None:
            return
        sites, scheduled = _registered_sites(tree)
        armed: Set[str] = set()
        fired: List[Tuple[str, str, int]] = []
        for rel in project.glob("fluidframework_tpu/**/*.py"):
            if rel == FAULTS_MODULE or "__pycache__" in rel:
                continue
            mod = project.parse(rel)
            if mod is None:
                continue
            for node in ast.walk(mod):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("fire", "due") \
                        and node.args:
                    lit = _const_str(node.args[0])
                    if lit is not None:
                        fired.append((lit, rel, node.lineno))
                lit = _const_str(node)
                if lit in sites:
                    armed.add(lit)
        for lit, rel, line in fired:
            if lit not in sites:
                yield self.project_finding(rel, line, (
                    f"fault site '{lit}' is fired here but not registered "
                    f"in testing/faults.py SITES — invisible to the fault "
                    f"matrix"))
        for site, line in sorted(sites.items()):
            if site not in armed:
                yield self.project_finding(FAULTS_MODULE, line, (
                    f"registered fault site '{site}' is armed nowhere in "
                    f"the package — hollow fault coverage"))
        for site, line in sorted(scheduled.items()):
            if site not in sites:
                yield self.project_finding(FAULTS_MODULE, line, (
                    f"SCHEDULED_SITES entry '{site}' is not a SITES key"))


# -- FL-DUR-GATE --------------------------------------------------------------


GATES_MODULE = "fluidframework_tpu/service/gates.py"
GATE_LIT_RE = re.compile(r"^(Catchup|Server)\.[A-Za-z][A-Za-z0-9_]*$")


def _registered_gates(tree: ast.Module) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if "GATES" in names and isinstance(node.value, ast.Dict):
            for key in node.value.keys:
                lit = _const_str(key)
                if lit is not None:
                    out[lit] = key.lineno
    return out


@register
class DurGateRule(ProjectRule):
    """Gate-registry drift: every ``Catchup.*``/``Server.*`` literal in
    the package must be a registered gate, and every registered gate
    must be read somewhere."""

    name = "FL-DUR-GATE"
    severity = "error"
    description = ("every Catchup.*/Server.* gate literal must be in "
                   "service/gates.py GATES, and every registered gate must "
                   "be read somewhere in the package")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        tree = project.parse(GATES_MODULE)
        if tree is None:
            return
        registered = _registered_gates(tree)
        used: Set[str] = set()
        for rel in project.glob("fluidframework_tpu/**/*.py"):
            if rel == GATES_MODULE or "__pycache__" in rel:
                continue
            mod = project.parse(rel)
            if mod is None:
                continue
            for node in ast.walk(mod):
                lit = _const_str(node)
                if lit is None or not GATE_LIT_RE.match(lit):
                    continue
                if lit in registered:
                    used.add(lit)
                else:
                    yield self.project_finding(rel, node.lineno, (
                        f"gate '{lit}' is read here but not registered in "
                        f"service/gates.py GATES — defaults drift silently"))
        for key, line in sorted(registered.items()):
            if key not in used:
                yield self.project_finding(GATES_MODULE, line, (
                    f"registered gate '{key}' is never read anywhere in "
                    f"the package — dead configuration"))
