"""Wire-completeness rule — a cross-file protocol contract.

Every dataclass in ``protocol/messages.py`` is a wire message: it must
have an encode and a decode path in ``protocol/wire.py`` (the single
definition point for framing and codecs, so a protocol bump can never ship
a client/server pair that disagree) and a round-trip test exercising it.
Dataclasses defined in ``protocol/wire.py`` ITSELF (the columnar batch
forms, e.g. ``ColumnBatch``) are wire messages too and carry the same
obligations — defining a batch layout next to the codecs does not exempt
it from registration or round-trip coverage.

The contract is purely structural so it stays checkable without importing
the package:

- ``protocol/wire.py`` defines ``encode_<snake_name>`` and
  ``decode_<snake_name>`` functions and lists the class name as a key of
  the ``MESSAGE_CODECS`` dict literal;
- some ``tests/test_wire*.py`` file references the class name (the shipped
  round-trip suite additionally asserts exhaustiveness dynamically, so a
  new dataclass fails BOTH this rule and that test until covered).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set

from .core import Finding, ProjectContext, ProjectRule, register

MESSAGES_PATH = "fluidframework_tpu/protocol/messages.py"
WIRE_PATH = "fluidframework_tpu/protocol/wire.py"
TEST_GLOB = "tests/test_wire*.py"


def snake_case(name: str) -> str:
    return re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name).lower()


def dataclass_names(tree: ast.Module) -> List[str]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = target.attr if isinstance(target, ast.Attribute) else \
                getattr(target, "id", None)
            if name == "dataclass":
                out.append(node.name)
                break
    return out


def _identifiers(tree: ast.Module) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            names.add(node.id)
        elif isinstance(node, ast.Attribute):
            names.add(node.attr)
        elif isinstance(node, ast.alias):
            names.add(node.name.split(".")[-1])
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            names.add(node.value)
    return names


def _codec_dict_keys(tree: ast.Module) -> Set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "MESSAGE_CODECS"
                for t in node.targets) and isinstance(node.value, ast.Dict):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return set()


@register
class WireCompletenessRule(ProjectRule):
    name = "FL-WIRE-COMPLETE"
    severity = "error"
    description = (
        "every dataclass in protocol/messages.py needs encode_/decode_ "
        "paths in protocol/wire.py (MESSAGE_CODECS) and a round-trip test"
    )

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        messages = project.parse(MESSAGES_PATH)
        if messages is None:
            return
        wire = project.parse(WIRE_PATH)
        classes = dataclass_names(messages)
        if wire is not None:
            # wire.py's own dataclasses (columnar batch forms) are wire
            # messages with the same codec + round-trip obligations.
            classes = classes + [c for c in dataclass_names(wire)
                                 if c not in classes]
        if not classes:
            return
        if wire is None:
            yield self.project_finding(
                MESSAGES_PATH, 1,
                f"{WIRE_PATH} is missing but {MESSAGES_PATH} defines "
                f"{len(classes)} wire dataclasses",
            )
            return
        wire_defs = {n.name for n in ast.walk(wire)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))}
        codec_keys = _codec_dict_keys(wire)
        test_files = project.glob(TEST_GLOB)
        test_idents: Set[str] = set()
        for tf in test_files:
            tree = project.parse(tf)
            if tree is not None:
                test_idents |= _identifiers(tree)
        for cls in classes:
            snake = snake_case(cls)
            for prefix in ("encode_", "decode_"):
                fn = prefix + snake
                if fn not in wire_defs:
                    yield self.project_finding(
                        WIRE_PATH, 1,
                        f"message dataclass {cls} has no {fn}() in "
                        f"{WIRE_PATH}; every wire message needs an "
                        "explicit encode and decode path",
                    )
            if cls not in codec_keys:
                yield self.project_finding(
                    WIRE_PATH, 1,
                    f"message dataclass {cls} is not registered in "
                    "MESSAGE_CODECS; the codec registry is the dispatch "
                    "surface drivers/services use",
                )
            if not test_files:
                yield self.project_finding(
                    MESSAGES_PATH, 1,
                    f"no {TEST_GLOB} round-trip suite exists to cover "
                    f"message dataclass {cls}",
                )
            elif cls not in test_idents:
                yield self.project_finding(
                    MESSAGES_PATH, 1,
                    f"message dataclass {cls} has no round-trip coverage "
                    f"in {TEST_GLOB}",
                )
