"""Imports every rule module so ``core.register`` sees them all.

Adding a rule = write it in the right themed module (or a new one) with
the ``@register`` decorator, then import that module here.
"""

from . import rules_concurrency  # noqa: F401
from . import rules_determinism  # noqa: F401
from . import rules_durability   # noqa: F401
from . import rules_errors       # noqa: F401
from . import rules_events       # noqa: F401
from . import rules_kernel       # noqa: F401
from . import rules_lifecycle    # noqa: F401
from . import rules_trace        # noqa: F401
from . import rules_wire         # noqa: F401
