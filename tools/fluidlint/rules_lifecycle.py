"""fluidleak — exception-path resource-lifecycle & error-hygiene rules.

The serving path's correctness rests on hand-maintained cleanup
protocols: the single-flight cache demands "``finish`` or ``abandon``
the key (use try/finally)" (`service/catchup_cache.py`), sockets need
``shutdown(SHUT_RDWR)`` *and* ``close()`` to unstick reader threads
(`drivers/network_driver.py`), and a leader that "died without reaching
its finally" strands a whole herd (`service/catchup.py`).  Nothing
*checked* that every exit path honors these pairings — a leaked flight,
an unclosed socket, or a silently-swallowed exception survives every
deterministic test by definition and only shows up as a production
hang.  This family closes that gap the way fluidlint closed it for
determinism and fluidrace for lock discipline: statically, over the
plain AST, using the exit-path enumerator in ``core.iter_exit_paths``.

Protocol pairs
--------------

``PROTOCOL_PAIRS`` maps opener method names to their accepted closers
(``begin -> finish | abandon``, ``acquire -> release``,
``open -> close``, ``shutdown -> close``).  Openers and closers match on
the *same receiver text* (``self.cache.begin`` pairs with
``self.cache.abandon``, never ``other.abandon``).  Site-specific pairs
are declared with a trailing comment on the opener's line::

    handle = self.store.grab(key)  # pairs-with: put_back, drop

Known limits (document, don't pretend): receiver matching is textual —
aliasing (``c = self.cache; c.abandon(k)``) is invisible; loops run
zero-or-one times; every except handler is assumed to catch (an
exception type no handler matches escaping unclosed is invisible);
functions too branchy for the path budget are declined, not guessed at;
closures that capture a resource do not count as a hand-off.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from .core import (ExitPath, Finding, ModuleContext, Rule,
                   iter_exit_paths, register)
from .rules_concurrency import (SERVING_SCOPE, _owner_phrase,
                                _walk_pruned as _fn_walk)

#: opener method name -> accepted closer method names (same receiver)
PROTOCOL_PAIRS: Dict[str, Tuple[str, ...]] = {
    "begin": ("finish", "abandon"),
    "acquire": ("release",),
    "open": ("close",),
    "shutdown": ("close",),
}

PAIRS_WITH_RE = re.compile(r"pairs-with:\s*([A-Za-z_][\w, ]*)")

#: constructors whose result owns an OS resource; the value must be
#: closed on every path, escape the function, or live in a ``with``.
RESOURCE_CTORS = {
    "open": "open",
    "socket.socket": "socket.socket",
    "socket.create_connection": "socket.create_connection",
    "concurrent.futures.ThreadPoolExecutor": "ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor": "ProcessPoolExecutor",
    "threading.Thread": "threading.Thread",
    # A child process is the heaviest leak in the table: an un-reaped
    # Popen holds a zombie entry + pipes for the parent's lifetime (the
    # fluidproc supervisor tracks every shard it spawns on self, which
    # is the hand-off shape; a fire-and-forget Popen local is a bug).
    "subprocess.Popen": "subprocess.Popen",
}
#: attribute-call constructors matched by method name (receiver-typed
#: resolution is beyond the AST): ``sock.makefile(...)`` ownership.
RESOURCE_CTOR_METHODS = {"makefile"}

#: calls that release a locally-owned resource (``kill``/``wait`` are the
#: Popen reap verbs)
RESOURCE_CLOSERS = {"close", "shutdown", "release", "terminate", "stop",
                    "join", "kill", "wait"}

#: method names that release member state (the double-close rule's
#: notion of a "release site")
RELEASE_VERBS = {"close", "shutdown", "release", "disconnect",
                 "unsubscribe", "clear", "stop", "cancel", "terminate"}

#: close-like method names whose definitions are checked for idempotency
CLOSE_METHODS = ("close", "shutdown")

#: telemetry / logging sinks: a broad except that reports through one of
#: these is surfacing the error, not swallowing it
_SINK_METHODS = {"send", "log", "warn", "warning", "exception", "error",
                 "critical", "debug", "info", "put", "bump"}

#: Whole underscore-words that mark a name as telemetry-ish.  Substring
#: matching is a laundering hole in BOTH branches: 'update_backlog' /
#: 'login' / 'catalog' as a direct call, 'self.backlog.put(...)' as a
#: receiver (generic _SINK_METHODS verbs make the receiver the only
#: real signal) — none of these may count as surfacing the error.
_SINK_WORDS = {"log", "logger", "logging", "telemetry", "warn", "warning",
               "metric", "metrics"}


def _is_sink_name(name: str) -> bool:
    return any(w in _SINK_WORDS for w in name.lower().split("_"))

_LOCKISH = re.compile(r"lock|mutex|cond|sem", re.IGNORECASE)


def _dotted(node: ast.AST) -> Optional[str]:
    """``self.cache`` / ``a.b.c`` / ``x`` as text, None for anything
    rooted in a call result or literal."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    """Every def in the module, nested included (each analyzed in its
    own right — the enumerator never descends into nested defs)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _exit_paths_for(m: ModuleContext, fn) -> Optional[List[ExitPath]]:
    """Memoized ``iter_exit_paths`` — PAIR and ESCAPE walk the same
    functions; enumerate once per (module, def)."""
    cache = getattr(m, "_leak_paths", None)
    if cache is None:
        cache = {}
        m._leak_paths = cache
    if id(fn) not in cache:
        cache[id(fn)] = iter_exit_paths(fn)
    return cache[id(fn)]


def _with_item_nodes(fn) -> Set[int]:
    """ids of every node inside a with-item's context expression: a
    resource opened there is closed by ``__exit__`` on every path."""
    out: Set[int] = set()
    for node in _fn_walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    out.add(id(sub))
    return out


def _finally_protected(fn, opener: ast.Call, is_closer) -> bool:
    """The opener sits in a try whose ``finally`` lexically contains a
    matching closer — every path out of that try (including exceptions
    and conditional closers the flow analysis cannot prove) runs it."""
    for node in _fn_walk(fn):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        in_body = any(id(sub) == id(opener)
                      for stmt in node.body
                      for sub in ast.walk(stmt))
        if not in_body:
            continue
        for stmt in node.finalbody:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and is_closer(sub):
                    return True
    return False


def _leaky_exits(paths: List[ExitPath], opener: ast.Call,
                 is_closer) -> List[ExitPath]:
    """Exit paths where the opener completed but no closer was even
    attempted afterwards."""
    bad: List[ExitPath] = []
    for p in paths:
        idx = None
        for i, ev in enumerate(p.events):
            if ev.kind == "call" and ev.node is opener:
                idx = i
                break
        if idx is None:
            continue  # opener not on this path (or never completed)
        closed = any(
            ev.kind in ("call", "call-raised") and is_closer(ev.node)
            for ev in p.events[idx + 1:]
        )
        if not closed:
            bad.append(p)
    return bad


def _exit_kinds(paths: List[ExitPath]) -> str:
    order = ("exception", "raise", "return", "fall")
    kinds = {p.kind for p in paths}
    return "/".join(k for k in order if k in kinds)


# -- FL-LEAK-PAIR --------------------------------------------------------------


@register
class ProtocolPairRule(Rule):
    name = "FL-LEAK-PAIR"
    severity = "error"
    scope = ("fluidframework_tpu/",)
    description = (
        "declared resource-protocol opener (begin/acquire/open/shutdown "
        "or '# pairs-with:') reaching a function exit with no matching "
        "closer on that path — close on every path (with / try-finally)"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for fn in _functions(m.tree):
            yield from self._check_fn(m, fn)

    def _openers(self, m: ModuleContext, fn):
        """(call, receiver text, closers) for every protocol opener in
        the function — table-matched method calls plus '# pairs-with:'
        annotated sites."""
        for node in _fn_walk(fn):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            recv = _dotted(node.func.value)
            if recv is None:
                continue
            comment = m.comments.get(node.lineno, "") or \
                m.comments.get(getattr(node, "end_lineno", 0), "")
            match = PAIRS_WITH_RE.search(comment)
            if match:
                closers = tuple(n.strip() for n in match.group(1).split(",")
                                if n.strip())
                if closers:
                    yield node, recv, closers
                    continue
            closers = PROTOCOL_PAIRS.get(node.func.attr)
            if closers is None:
                continue
            if node.func.attr == "shutdown" and node.keywords:
                # shutdown->close is SOCKET protocol (shutdown(how)
                # takes a lone positional).  Keyword args mark the
                # Executor.shutdown(wait=..., cancel_futures=...)
                # signature, which IS the terminal call — there is no
                # closer to demand.
                continue
            yield node, recv, closers

    def _check_fn(self, m: ModuleContext, fn) -> Iterator[Finding]:
        openers = list(self._openers(m, fn))
        if not openers:
            return
        with_nodes = _with_item_nodes(fn)
        paths = None
        for call, recv, closers in openers:
            if id(call) in with_nodes:
                continue  # __exit__ closes on every path

            def is_closer(c: ast.AST, recv=recv, closers=closers) -> bool:
                return (isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Attribute)
                        and c.func.attr in closers
                        and _dotted(c.func.value) == recv)

            if _finally_protected(fn, call, is_closer):
                continue
            if paths is None:
                paths = _exit_paths_for(m, fn)
            if paths is None:
                break  # too branchy: decline the whole function
            bad = _leaky_exits(paths, call, is_closer)
            if bad:
                want = "/".join(f".{c}()" for c in closers)
                yield m.finding(
                    self, call,
                    f"'.{call.func.attr}()' on '{recv}' "
                    f"{_owner_phrase(fn.name)} can exit via "
                    f"{_exit_kinds(bad)} with no {want} on that "
                    f"path; close the protocol on every path "
                    "(try/finally) or annotate the intended pair with "
                    "'# pairs-with:'",
                )


# -- FL-LEAK-ESCAPE ------------------------------------------------------------


@register
class ResourceEscapeRule(Rule):
    name = "FL-LEAK-ESCAPE"
    severity = "error"
    scope = ("fluidframework_tpu/",)
    description = (
        "locally-constructed resource (socket, open() handle, makefile, "
        "executor, non-daemon thread) neither closed on every path nor "
        "escaping via return/self./container/argument — use 'with'"
    )

    def _constructions(self, m: ModuleContext, fn):
        """(local name, ctor label, call) for resource constructors
        assigned to a plain local name."""
        for node in _fn_walk(fn):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if len(targets) != 1 or not isinstance(targets[0], ast.Name):
                continue
            label = None
            q = m.imports.resolve(value.func)
            if q in RESOURCE_CTORS:
                label = RESOURCE_CTORS[q]
                if q == "threading.Thread" and any(
                        kw.arg == "daemon"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value for kw in value.keywords):
                    continue  # daemon threads are fire-and-forget
            elif isinstance(value.func, ast.Attribute) and \
                    value.func.attr in RESOURCE_CTOR_METHODS:
                label = f".{value.func.attr}"
            if label is not None:
                yield targets[0].id, label, value

    @staticmethod
    def _mentions_outside_calls(node: ast.AST, name: str) -> bool:
        """``name`` appears in the expression in a value position — NOT
        inside a call subtree.  ``return rfile`` hands the resource off;
        ``return rfile.read(4)`` hands off bytes read *from* it (the
        Call branch of ``_escapes`` separately catches the resource
        passed as an argument)."""
        stack = [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, ast.Call):
                continue
            if isinstance(cur, ast.Name) and cur.id == name:
                return True
            stack.extend(ast.iter_child_nodes(cur))
        return False

    @classmethod
    def _escapes(cls, fn, name: str, ctor: ast.Call) -> bool:
        """The resource is handed off: returned/yielded, stored on self
        or into a container, or passed as a call argument."""
        for node in _fn_walk(fn):
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                if cls._mentions_outside_calls(node, name):
                    return True
            elif isinstance(node, ast.Assign):
                if node.value is ctor:
                    continue
                stores_out = any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in node.targets)
                if stores_out and cls._mentions_outside_calls(node.value,
                                                              name):
                    return True
            elif isinstance(node, ast.Call) and node is not ctor:
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    if any(isinstance(sub, ast.Name) and sub.id == name
                           for sub in ast.walk(arg)):
                        return True
        return False

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for fn in _functions(m.tree):
            constructions = list(self._constructions(m, fn))
            if not constructions:
                continue
            with_nodes = _with_item_nodes(fn)
            for name, label, ctor in constructions:
                if id(ctor) in with_nodes:
                    continue
                if self._escapes(fn, name, ctor):
                    continue

                def is_closer(c: ast.AST, name=name) -> bool:
                    return (isinstance(c, ast.Call)
                            and isinstance(c.func, ast.Attribute)
                            and c.func.attr in RESOURCE_CLOSERS
                            and isinstance(c.func.value, ast.Name)
                            and c.func.value.id == name)

                if _finally_protected(fn, ctor, is_closer):
                    continue
                paths = _exit_paths_for(m, fn)
                if paths is None:
                    break
                bad = _leaky_exits(paths, ctor, is_closer)
                if bad:
                    yield m.finding(
                        self, ctor,
                        f"resource '{name}' ({label}) constructed "
                        f"{_owner_phrase(fn.name)} can exit via "
                        f"{_exit_kinds(bad)} neither closed nor "
                        "handed off; wrap it in 'with' or close it in a "
                        "try/finally",
                    )


# -- FL-LEAK-SWALLOW -----------------------------------------------------------


@register
class SwallowedExceptionRule(Rule):
    name = "FL-LEAK-SWALLOW"
    severity = "error"
    scope = SERVING_SCOPE
    description = (
        "bare/broad except on a serving path that neither re-raises, "
        "uses the caught exception, nor reports through a telemetry/"
        "logging sink — failures vanish instead of surfacing"
    )

    _BROAD = ("Exception", "BaseException")

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for fn in _functions(m.tree):
            for node in _fn_walk(fn):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                label = self._broad_label(m, node)
                if label is None:
                    continue
                if self._surfaces(node):
                    continue
                yield m.finding(
                    self, node,
                    f"broad '{label}' {_owner_phrase(fn.name)} swallows "
                    "the error on a serving path (no re-raise, no "
                    "telemetry); re-raise, narrow the exception type, or "
                    "send an event through the telemetry logger",
                )

    def _broad_label(self, m: ModuleContext,
                     node: ast.ExceptHandler) -> Optional[str]:
        if node.type is None:
            return "except:"
        # `except (Exception, ValueError):` is the same front door as
        # `except Exception:` — one broad member makes the tuple broad
        types = node.type.elts if isinstance(node.type, ast.Tuple) \
            else [node.type]
        for t in types:
            q = m.imports.resolve(t)
            if q in self._BROAD:
                return f"except {q}"
        return None

    @staticmethod
    def _surfaces(handler: ast.ExceptHandler) -> bool:
        """The handler does something with the failure: re-raises,
        references the bound exception, or calls a telemetry sink."""
        for node in _fn_walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if handler.name and isinstance(node, ast.Name) and \
                    node.id == handler.name:
                return True
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func) or ""
                attr = node.func.attr \
                    if isinstance(node.func, ast.Attribute) else ""
                parts = dotted.split(".")
                if any(_is_sink_name(p) for p in parts[:-1]) \
                        and (attr in _SINK_METHODS or not attr):
                    return True
                if _is_sink_name(parts[-1]):
                    return True
        return False


# -- FL-LEAK-FINALLY-MASK ------------------------------------------------------


@register
class FinallyMaskRule(Rule):
    name = "FL-LEAK-FINALLY-MASK"
    severity = "error"
    scope = ("fluidframework_tpu/",)
    description = (
        "return / raise X / break / continue inside a finally block — "
        "silently discards any in-flight exception (a bare 're-raise' "
        "raise is fine)"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for fn in _functions(m.tree):
            # _fn_walk yields ancestors first; a Try nested inside an
            # outer finalbody was already scanned by that finalbody's
            # walk — visiting it again would report every statement in
            # ITS finalbody twice.
            scanned: Set[int] = set()
            for node in _fn_walk(fn):
                if not isinstance(node, ast.Try) or not node.finalbody:
                    continue
                if id(node) in scanned:
                    continue
                for stmt in node.finalbody:
                    for sub in _fn_walk(stmt):
                        if isinstance(sub, ast.Try):
                            scanned.add(id(sub))
                    yield from self._check_finally(m, fn, stmt)

    def _check_finally(self, m: ModuleContext, fn,
                       root: ast.stmt) -> Iterator[Finding]:
        # loops *inside* the finally own their break/continue
        loop_subtrees: Set[int] = set()
        for node in _fn_walk(root):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                for sub in ast.walk(node):
                    if sub is not node:
                        loop_subtrees.add(id(sub))
        # a raise in the BODY of a finally-local try that has handlers
        # is (assumed) caught before it can mask anything; orelse and
        # handler bodies stay unprotected
        caught_subtrees: Set[int] = set()
        for node in _fn_walk(root):
            if isinstance(node, ast.Try) and node.handlers:
                for stmt in node.body:
                    for sub in _fn_walk(stmt):
                        caught_subtrees.add(id(sub))
        for node in _fn_walk(root):
            if isinstance(node, ast.Return):
                kind = "'return'"
            elif isinstance(node, ast.Raise) and node.exc is not None \
                    and id(node) not in caught_subtrees:
                kind = "'raise'"
            elif isinstance(node, (ast.Break, ast.Continue)) and \
                    id(node) not in loop_subtrees:
                kind = "'break'" if isinstance(node, ast.Break) \
                    else "'continue'"
            else:
                continue
            yield m.finding(
                self, node,
                f"{kind} inside 'finally' {_owner_phrase(fn.name)} masks "
                "an in-flight exception — the error silently disappears; "
                "move the statement out of the finally block",
            )


# -- FL-LEAK-GEN-HOLD ----------------------------------------------------------


@register
class GeneratorHoldRule(Rule):
    name = "FL-LEAK-GEN-HOLD"
    severity = "error"
    scope = SERVING_SCOPE + ("fluidframework_tpu/protocol/",)
    description = (
        "'yield' while inside a 'with' over a lock/resource in a "
        "generator on a serving path — an abandoned generator pins the "
        "resource forever; snapshot under the lock, yield outside"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for fn in _functions(m.tree):
            # One finding per offending yield: nested resource withs
            # around the same yield are ONE defect (the outermost walk
            # order of _fn_walk reports it against the outermost with).
            reported: Set[int] = set()
            for node in _fn_walk(fn):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                held = [item for item in node.items
                        if self._resource_like(m, item.context_expr)]
                if not held:
                    continue
                for sub in _fn_walk(node):
                    if not isinstance(sub, (ast.Yield, ast.YieldFrom)):
                        continue
                    if id(sub) in reported:
                        continue
                    reported.add(id(sub))
                    recv = _dotted(held[0].context_expr) or "resource"
                    yield m.finding(
                        self, sub,
                        f"'yield' inside 'with {recv}' "
                        f"{_owner_phrase(fn.name)}: a suspended "
                        "generator holds the resource across its "
                        "consumer's loop body, and an abandoned one "
                        "pins it forever — snapshot under the "
                        "resource and yield outside the with",
                    )
                    break  # one finding per with-block

    @staticmethod
    def _resource_like(m: ModuleContext, expr: ast.AST) -> bool:
        dotted = _dotted(expr)
        if dotted is not None:
            return bool(_LOCKISH.search(dotted.split(".")[-1]))
        if isinstance(expr, ast.Call):
            q = m.imports.resolve(expr.func)
            if q == "open" or q in RESOURCE_CTORS:
                return True
            if isinstance(expr.func, ast.Attribute):
                return bool(_LOCKISH.search(expr.func.attr)) \
                    or expr.func.attr in RESOURCE_CTOR_METHODS
        return False


# -- FL-LEAK-DOUBLE-CLOSE ------------------------------------------------------


@register
class DoubleCloseRule(Rule):
    name = "FL-LEAK-DOUBLE-CLOSE"
    severity = "warning"
    scope = ("fluidframework_tpu/",)
    description = (
        "a close/shutdown method reachable from more than one call path "
        "(an internal self.close() caller, or 2+ tracked call sites) "
        "that is not idempotency-guarded — double-close must be a no-op"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        bindings = self._instance_bindings(m)
        for cls in self._classes(m.tree):
            yield from self._check_class(m, cls, bindings)

    @staticmethod
    def _classes(tree: ast.AST) -> Iterator[ast.ClassDef]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                yield node

    def _instance_bindings(self, m: ModuleContext) -> Dict[str, str]:
        """receiver text -> class name, from ``x = C(...)`` /
        ``self.y = C(...)`` where C is a class defined in this module."""
        class_names = {c.name for c in self._classes(m.tree)}
        out: Dict[str, str] = {}
        for node in ast.walk(m.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)) or \
                    not isinstance(node.value, ast.Call):
                continue
            func = node.value.func
            if not isinstance(func, ast.Name) or \
                    func.id not in class_names:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                recv = _dotted(t)
                if recv is not None:
                    out[recv] = func.id
        return out

    def _check_class(self, m: ModuleContext, cls: ast.ClassDef,
                     bindings: Dict[str, str]) -> Iterator[Finding]:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        for name in CLOSE_METHODS:
            fn = methods.get(name)
            if fn is None:
                continue
            sites = self._release_sites(fn)
            if not sites:
                continue  # closes nothing worth guarding
            if not self._multi_close(m, cls, name, methods, bindings):
                continue
            if self._guarded(fn, sites):
                continue
            yield m.finding(
                self, fn,
                f"{name}() of {cls.name} is reachable from more than "
                "one call path but releases member state unguarded — a "
                "second call re-runs the release; make double-close a "
                "no-op (early return on a closed flag, or a None'd "
                "handle check)",
            )

    @staticmethod
    def _release_sites(fn) -> List[ast.Call]:
        """Calls in the method that release self-rooted member state."""
        out = []
        for node in _fn_walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in RELEASE_VERBS:
                recv = _dotted(node.func.value)
                if recv is not None and recv.startswith("self."):
                    out.append(node)
        return out

    def _multi_close(self, m: ModuleContext, cls: ast.ClassDef,
                     name: str, methods, bindings) -> bool:
        # (a) a sibling method calls self.<close>() — together with the
        # public entry point that is two reachable close paths
        for other_name, other in methods.items():
            if other_name == name:
                continue
            for node in _fn_walk(other):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == name and \
                        _dotted(node.func.value) == "self":
                    return True
        # (b) two or more module-wide call sites on tracked instances
        count = 0
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == name:
                recv = _dotted(node.func.value)
                if recv is not None and bindings.get(recv) == cls.name:
                    count += 1
        return count >= 2

    @staticmethod
    def _method_stmts(fn) -> Iterator[ast.stmt]:
        """Top-level statements, looking through `with` blocks: the
        idempotency flag is routinely checked under the state lock
        (`with self._state_lock: if self._closed: return`)."""
        stack = list(reversed(fn.body))
        while stack:
            stmt = stack.pop()
            yield stmt
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                stack.extend(reversed(stmt.body))

    @classmethod
    def _guarded(cls, fn, sites: List[ast.Call]) -> bool:
        # (1) method-level early-return guard on member state
        for stmt in cls._method_stmts(fn):
            if isinstance(stmt, ast.If) and any(
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                    for sub in ast.walk(stmt.test)) and any(
                    isinstance(s, ast.Return) for s in stmt.body):
                return True
        # (2) every release site individually guarded: under an If whose
        # test reads self state, or inside a try with handlers
        site_ids = {id(s) for s in sites}
        guarded: Set[int] = set()
        for node in _fn_walk(fn):
            if isinstance(node, ast.Try) and node.handlers:
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        if id(sub) in site_ids:
                            guarded.add(id(sub))
            elif isinstance(node, ast.If) and any(
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                    for sub in ast.walk(node.test)):
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        if id(sub) in site_ids:
                            guarded.add(id(sub))
        return site_ids <= guarded
