"""Event-emission safety.

``emit()`` iterates a listener list that handlers can mutate re-entrantly
(``once`` unsubscribes itself; app handlers subscribe siblings).  Python's
list iterator over a mutating list skips or double-fires entries, so every
emit loop must iterate a *snapshot* (``list(...)``/``tuple(...)``) of the
listener collection — never the live list.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from .core import Finding, ModuleContext, Rule, register

_LISTENER_ATTR = re.compile(
    r"(listener|subscriber|handler|observer|callback)s?$", re.IGNORECASE)

_SNAPSHOT_CALLS = {"list", "tuple", "sorted"}


def _listener_attr_name(node: ast.AST) -> Optional[str]:
    """The listener-collection attribute an expression reads, if any.

    Matches ``self._listeners``, ``self._listeners[event]``,
    ``self._listeners.get(event, [])``, ``obj.handlers.values()`` — the
    shapes that yield the LIVE list."""
    if isinstance(node, ast.Attribute):
        if _LISTENER_ATTR.search(node.attr):
            return node.attr
        # .get(...) / .values() hang off the collection attribute
        return None
    if isinstance(node, ast.Subscript):
        return _listener_attr_name(node.value)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in ("get", "values"):
        return _listener_attr_name(node.func.value)
    return None


@register
class EmitIterationRule(Rule):
    name = "FL-EVENT-EMITITER"
    severity = "error"
    scope = ("fluidframework_tpu/",)
    description = (
        "emit loops must iterate a snapshot (list(...)) of the listener "
        "collection; handlers may subscribe/unsubscribe during dispatch"
    )

    def check(self, m: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(m.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            it = node.iter
            if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                    and it.func.id in _SNAPSHOT_CALLS:
                continue  # snapshot taken — safe
            name = _listener_attr_name(it)
            if name is not None:
                yield m.finding(
                    self, node,
                    f"iterating live listener collection '{name}'; a "
                    "handler that subscribes/unsubscribes during dispatch "
                    "corrupts this loop — iterate "
                    "list(...) of it instead",
                )
