"""chaos: run named fault plans against the serving stack and report.

The CLI front end of the faultline engine (ISSUE 9): each named plan is a
deterministic fault schedule driven through ``run_chaos_with_oracle`` —
mixed multi-shard traffic under injected durable-append outages, torn
writes, stale summary serves, laggard clients, and shard kills — and a
scenario only counts as SURVIVED when the final per-document summaries
are byte-identical to the fault-free oracle twin, every plan point fired,
and no retry loop exceeded its budget.

    python -m tools.chaos                         # all plans, 3 seeds
    python -m tools.chaos --plan kill-quake --seeds 5
    python -m tools.chaos --out BENCH_chaos_cpu_r09.json

Emits ONE JSON document: per-plan scenarios survived, retries/op, p99
recovery ticks (virtual — schedule distance, not wall time), fault and
retry counter totals, plus a TCP smoke section that exercises the wire
seams (rpc send/recv faults, session-write stall → demotion) against an
in-thread standalone server.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.service.sharding import ShardRouter  # noqa: E402
from fluidframework_tpu.tools.bench_harness import write_bench_json  # noqa: E402
from fluidframework_tpu.testing.faults import (  # noqa: E402
    FaultPlan, FaultPoint,
)
from fluidframework_tpu.testing.load import (  # noqa: E402
    ChaosLoadSpec, chaos_doc_ids, percentile as _percentile,
    run_chaos_with_oracle,
)

DOCS = 8
STEPS = 240
SHARD_IDS = [f"shard{i:02d}" for i in range(4)]


def _doc_ids():
    return chaos_doc_ids(DOCS)


def _two_docs_on_distinct_shards():
    """Two documents whose rendezvous owners differ — so a double-kill
    plan really takes down two shards."""
    router = ShardRouter(SHARD_IDS)
    docs = _doc_ids()
    first = docs[0]
    for other in docs[1:]:
        if router.owner(other) != router.owner(first):
            return first, other
    return first, docs[-1]


def build_plan(name: str, seed: int) -> FaultPlan:
    docs = _doc_ids()
    if name == "mixed":
        return FaultPlan.generate(seed, docs, STEPS)
    if name == "append-storm":
        points = []
        for i, doc in enumerate(docs):
            points.append(FaultPoint("oplog.append", "fail", doc=doc,
                                     at=2 + i, count=2))
        points.append(FaultPoint("oplog.append", "torn", at=10, arg=0.3))
        points.append(FaultPoint("oplog.append", "torn", at=40, arg=0.7))
        points.append(FaultPoint("oplog.flush", "skip_fsync", at=5))
        return FaultPlan(seed=seed, points=tuple(points))
    if name == "kill-quake":
        a, b = _two_docs_on_distinct_shards()
        return FaultPlan(seed=seed, points=(
            FaultPoint("shard.kill", "kill", doc=a, at=STEPS // 3),
            FaultPoint("shard.kill", "kill", doc=b, at=2 * STEPS // 3),
            FaultPoint("oplog.append", "fail", doc=a, at=3),
        ))
    if name == "laggard-town":
        points = [
            FaultPoint("client.stall", "stall", doc=doc,
                       at=STEPS // 4 + 3 * i, arg=8.0)
            for i, doc in enumerate(docs[:4])
        ]
        # windowed so the LATE JOIN's load is really served stale (see
        # FaultPlan.generate — at=1 alone fires vacuously at setup)
        points.append(FaultPoint("storage.read", "stale", doc=docs[0],
                                 at=1, count=3))
        return FaultPlan(seed=seed, points=tuple(points))
    raise SystemExit(f"unknown plan {name!r} (have: {', '.join(PLANS)})")


PLANS = ("mixed", "append-storm", "kill-quake", "laggard-town")

#: handled by the fluidproc runner, not run_plan: the kill-quake shape
#: against REAL shard-host processes (SIGKILL, per-shard logs, adoption).
PROC_PLANS = ("kill-quake-proc",)

#: handled by the fluidscale storm runner (ISSUE 15): a catch-up herd
#: through the REAL fold tier with the ``catchup.slow``/``catchup.fail``
#: seams armed — shed, degraded-mode, and fold-crash recovery must all
#: converge byte-identically to the never-shed oracle.
STORM_PLANS = ("fold-squeeze", "stream-squeeze")


def run_fold_squeeze(seeds: int) -> dict:
    """The catchup-storm scenario as a chaos plan: herd joins hammer the
    adaptive-admission fold lane (slots deliberately scarce), a slow
    fold stretches the measured cost, a fold crash exercises the
    single-flight abandon + retry — and every seed must converge
    byte-identically to its never-shed single-shard oracle with full
    fault coverage and the admission counters balancing exactly."""
    from fluidframework_tpu.testing.scenarios import (
        build_scenario, oracle_spec, run_swarm)

    survived = 0
    ops = 0
    fault_totals: dict = {}
    failures: list = []
    storm_totals = {"shed": 0, "degraded": 0, "folds": 0, "warm": 0,
                    "retries": 0, "fold_errors": 0}
    for seed in range(seeds):
        spec = build_scenario("catchup-storm", seed=seed, clients=1200,
                              docs=8, shards=4)
        chaos = run_swarm(spec)
        oracle = run_swarm(oracle_spec(spec, chaos))
        admission = chaos.storm.get("admission") or {}
        balanced = (admission.get("catchup.requests", 0)
                    == admission.get("catchup.admitted", 0)
                    + admission.get("catchup.shed", 0)
                    + admission.get("catchup.degraded", 0))
        covered = all(
            chaos.fault_counts.get(f"{p.site}:{p.kind}", 0) > 0
            for p in spec.plan.points)
        ok = (chaos.sampled_digests == oracle.sampled_digests
              and chaos.per_doc_head == oracle.per_doc_head
              and chaos.storm.get("served") == chaos.storm.get("requests")
              and balanced and covered)
        if ok:
            survived += 1
        else:
            failures.append({
                "seed": seed,
                "digest_match":
                    chaos.sampled_digests == oracle.sampled_digests,
                "head_match": chaos.per_doc_head == oracle.per_doc_head,
                "balanced": balanced,
                "covered": covered,
            })
        ops += chaos.sequenced_ops
        for key in storm_totals:
            storm_totals[key] += int(chaos.storm.get(key) or 0)
        for k, v in sorted(chaos.fault_counts.items()):
            fault_totals[k] = fault_totals.get(k, 0) + v
    return {
        "scenarios": seeds,
        "survived": survived,
        "failures": failures,
        "sequenced_ops": ops,
        "storm": storm_totals,
        "fault_counts": fault_totals,
    }


def run_stream_squeeze(seeds: int) -> dict:
    """The catchup-storm scenario with the STREAMING fold attached
    (ISSUE 16) and its chaos seams armed: a stall window parked over the
    herd re-entry makes the published summaries age past the stream lag
    — those catch-ups must DEGRADE to the ordinary cold-fold path,
    deterministically, with the downgrade visible in the lane counters —
    and a poll-round crash mid-selection must be swallowed, counted, and
    leave the unpicked documents foldable next round.  Every seed must
    converge byte-identically to its never-shed oracle twin, the
    streaming lane must carry serves outside the stall window, and the
    truncation totals must show the log really shrank behind the
    continuously-published summaries."""
    import dataclasses

    from fluidframework_tpu.testing.scenarios import (
        build_scenario, oracle_spec, run_swarm)

    survived = 0
    ops = 0
    fault_totals: dict = {}
    failures: list = []
    storm_totals = {"stream": 0, "warm": 0, "folds": 0, "shed": 0,
                    "degraded": 0, "retries": 0, "fold_errors": 0}
    stream_totals = {"stalls": 0, "crashes": 0, "truncations": 0,
                     "truncated_msgs": 0}
    for seed in range(seeds):
        spec = build_scenario("catchup-storm", seed=seed, clients=1200,
                              docs=8, shards=4)
        # The streaming seams arm ON TOP of the storm's own
        # catchup.slow/catchup.fail.  Polls run once per tick, so
        # stall-occurrence ≈ tick: the 8-round window starts just
        # before the herd cohort's jittered arrivals (herd phase ends
        # around tick 88) — the downgrade happens while stormers land.
        plan = FaultPlan(seed=seed, points=spec.plan.points + (
            FaultPoint("stream.stall", "stall", at=85, count=8),
            FaultPoint("stream.crash", "fail", at=40),
        ))
        spec = dataclasses.replace(spec, stream=True, plan=plan)
        chaos = run_swarm(spec)
        oracle = run_swarm(oracle_spec(spec, chaos))
        covered = all(
            chaos.fault_counts.get(f"{p.site}:{p.kind}", 0) > 0
            for p in spec.plan.points)
        sf = chaos.storm.get("streamfold") or {}
        ok = (chaos.sampled_digests == oracle.sampled_digests
              and chaos.per_doc_head == oracle.per_doc_head
              and chaos.storm.get("served") == chaos.storm.get("requests")
              and covered
              and sf.get("stalls", 0) > 0
              and sf.get("crashes", 0) > 0
              and sf.get("truncations", 0) > 0)
        if ok:
            survived += 1
        else:
            failures.append({
                "seed": seed,
                "digest_match":
                    chaos.sampled_digests == oracle.sampled_digests,
                "head_match": chaos.per_doc_head == oracle.per_doc_head,
                "covered": covered,
                "streamfold": sf,
            })
        ops += chaos.sequenced_ops
        for key in storm_totals:
            storm_totals[key] += int(chaos.storm.get(key) or 0)
        for key in stream_totals:
            stream_totals[key] += int(sf.get(key) or 0)
        for k, v in sorted(chaos.fault_counts.items()):
            fault_totals[k] = fault_totals.get(k, 0) + v
    return {
        "scenarios": seeds,
        "survived": survived,
        "failures": failures,
        "sequenced_ops": ops,
        "storm": storm_totals,
        "streamfold": stream_totals,
        "fault_counts": fault_totals,
    }


def run_proc_quake(seeds: int) -> dict:
    """The kill-quake plan's process variant (ISSUE 12): a steady-typing
    swarm against the REAL out-of-process tier with two scheduled
    ``proc.kill`` points — each SIGKILLs the current owner process of a
    pinned document at its tick — verified against the fault-free
    single-shard in-proc oracle twin, plus full coverage accounting."""
    import dataclasses

    from fluidframework_tpu.testing.scenarios import (
        build_scenario, oracle_spec, run_swarm)

    a, b = _two_docs_on_distinct_shards_swarm()
    survived = 0
    ops = 0
    fault_totals: dict = {}
    failures: list = []
    for seed in range(seeds):
        spec = build_scenario("steady-typing", seed=seed, clients=1200,
                              docs=8, shards=4)
        total = spec.ticks
        plan = FaultPlan(seed=seed, points=(
            FaultPoint("proc.kill", "kill", doc=a, at=total // 3),
            FaultPoint("proc.kill", "kill", doc=b, at=2 * total // 3),
        ))
        spec = dataclasses.replace(spec, plan=plan, out_of_proc=True,
                                   sample_every=4)
        chaos = run_swarm(spec)
        oracle = run_swarm(oracle_spec(spec, chaos))
        kills_executed = chaos.fault_counts.get("proc.kill:kill", 0)
        ok = (chaos.sampled_digests == oracle.sampled_digests
              and chaos.per_doc_head == oracle.per_doc_head
              and kills_executed == 2)
        if ok:
            survived += 1
        else:
            failures.append({
                "seed": seed,
                "digest_match":
                    chaos.sampled_digests == oracle.sampled_digests,
                "head_match": chaos.per_doc_head == oracle.per_doc_head,
                "kills_executed": kills_executed,
            })
        ops += chaos.sequenced_ops
        for k, v in sorted(chaos.fault_counts.items()):
            fault_totals[k] = fault_totals.get(k, 0) + v
    return {
        "scenarios": seeds,
        "survived": survived,
        "failures": failures,
        "sequenced_ops": ops,
        "fault_counts": fault_totals,
    }


def _two_docs_on_distinct_shards_swarm():
    """Two swarm documents whose rendezvous owners differ under the
    4-shard layout, so the double proc-kill really takes two processes."""
    router = ShardRouter(SHARD_IDS)
    docs = [f"sw-{i:04d}" for i in range(8)]
    first = docs[0]
    for other in docs[1:]:
        if router.owner(other) != router.owner(first):
            return first, other
    return first, docs[-1]


def load_plan_file(path: str, seed: int) -> FaultPlan:
    """A plan file is JSON: ``{"points": [{"site": ..., "kind": ...,
    "at": N, "count": N, "doc": ..., "shard": ..., "arg": X}, ...]}`` —
    unknown sites/kinds fail loudly via FaultPoint.validate."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    points = tuple(
        FaultPoint(
            site=p["site"], kind=p["kind"], at=int(p.get("at", 1)),
            count=int(p.get("count", 1)), doc=p.get("doc"),
            shard=p.get("shard"), arg=float(p.get("arg", 0.0)),
        )
        for p in doc.get("points", ())
    )
    return FaultPlan(seed=doc.get("seed", seed), points=points)


def run_plan(name: str, seeds: int, workdir: str,
             plan_file: str = None) -> dict:
    survived = 0
    recovery: list = []
    fault_totals: dict = {}
    retry_totals: dict = {}
    ops = retries = 0
    failures: list = []
    for seed in range(seeds):
        spec = ChaosLoadSpec(
            seed=seed, shards=4, docs=DOCS, clients_per_doc=2,
            steps=STEPS,
            plan=(load_plan_file(plan_file, seed) if plan_file
                  else build_plan(name, seed)),
            dir=os.path.join(workdir, f"{name}-{seed}"),
        )
        chaos, oracle = run_chaos_with_oracle(spec)
        ok = (chaos.per_doc_digest == oracle.per_doc_digest
              and chaos.per_doc_head == oracle.per_doc_head
              and chaos.unfired == [])
        if ok:
            survived += 1
        else:
            failures.append({
                "seed": seed,
                "digest_match": chaos.per_doc_digest == oracle.per_doc_digest,
                "unfired": chaos.unfired,
            })
        recovery.extend(chaos.recovery_ticks)
        ops += chaos.sequenced_ops
        retries += chaos.retry_counts.get("retry.retries", 0)
        for k, v in sorted(chaos.fault_counts.items()):
            fault_totals[k] = fault_totals.get(k, 0) + v
        for k, v in sorted(chaos.retry_counts.items()):
            retry_totals[k] = retry_totals.get(k, 0) + v
    recovery.sort()
    return {
        "scenarios": seeds,
        "survived": survived,
        "failures": failures,
        "sequenced_ops": ops,
        "retries_per_op": round(retries / ops, 5) if ops else 0.0,
        "budget_exhaustions": retry_totals.get("retry.exhausted", 0),
        "recovery_samples": len(recovery),
        "recovery_ticks_p50": round(_percentile(recovery, 0.50), 4),
        "recovery_ticks_p99": round(_percentile(recovery, 0.99), 4),
        "fault_counts": fault_totals,
        "retry_counts": retry_totals,
    }


def tcp_smoke() -> dict:
    """One wire scenario against an in-thread standalone server: client
    rpc send failures (retried), a duplicated and a delayed broadcast
    frame (watermark dedup + park/repair), and a server-side
    session-write stall (demotion → backfill-from-oplog)."""
    from fluidframework_tpu.drivers.network_driver import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.loader.delta_manager import DeltaManager
    from fluidframework_tpu.protocol.messages import (MessageType,
                                                      RawOperation)
    from fluidframework_tpu.runtime.container import ContainerRuntime
    from fluidframework_tpu.service.orderer import LocalOrderingService
    from fluidframework_tpu.service.retry import RetryPolicy
    from fluidframework_tpu.service.server import OrderingServer
    from fluidframework_tpu.testing.faults import FaultInjector

    server_faults = FaultInjector(FaultPlan(points=(
        FaultPoint("session.write", "stall", at=2, count=2),)))
    server = OrderingServer(LocalOrderingService(), port=0,
                            faults=server_faults)
    server.start_in_thread()
    client_faults = FaultInjector(FaultPlan(points=(
        FaultPoint("rpc.send", "fail", at=4, count=2),
        FaultPoint("rpc.recv", "duplicate", doc="smoke", at=3),
        FaultPoint("rpc.recv", "delay", doc="smoke", at=5),
    )))
    factory = NetworkDocumentServiceFactory(
        port=server.port, faults=client_faults,
        retry=RetryPolicy(max_attempts=4, base_delay=0.01))
    try:
        runtime = ContainerRuntime()
        runtime.create_datastore("ds")
        doc = factory.create_document("smoke", runtime.summarize())
        conn = doc.connection()
        dm = DeltaManager(factory.resolve("smoke"))
        dm.connect("cA")
        dm.note_delivered(doc.delta_storage.head())
        got = []
        dm.subscribe(lambda m: got.append(m.seq))
        ref = conn.head_seq
        for i in range(10):
            ref = conn.submit(RawOperation(
                client_id="cA", client_seq=i + 1, ref_seq=ref,
                type=MessageType.OP, contents={"i": i})).seq
        deadline = time.time() + 15
        while time.time() < deadline and dm.last_delivered_seq < ref:
            time.sleep(0.02)
        return {
            "converged": dm.last_delivered_seq == ref,
            "in_order": got == sorted(set(got)),
            "demotions": server.broadcaster.counters.get("demotions"),
            "client_demotions_seen": conn.demotions_seen,
            "rpc_retries": factory._rpc.retry_counters.get("retry.retries"),
            "unfired_client": [p.label()
                               for p in client_faults.unfired()],
            "unfired_server": [p.label()
                               for p in server_faults.unfired()],
        }
    finally:
        factory.close()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="run named fault plans against the serving stack")
    parser.add_argument("--plan",
                        choices=PLANS + PROC_PLANS + STORM_PLANS + ("all",),
                        default="all")
    parser.add_argument("--plan-file", default=None,
                        help="run a custom JSON fault plan instead of "
                             "the named ones")
    parser.add_argument("--seeds", type=int, default=3)
    parser.add_argument("--out", default=None,
                        help="write the JSON report here (default stdout)")
    parser.add_argument("--no-tcp", action="store_true",
                        help="skip the TCP smoke section")
    args = parser.parse_args(argv)

    t0 = time.time()
    plans = PLANS if args.plan == "all" else (args.plan,)
    if args.plan_file:
        plans = (os.path.basename(args.plan_file),)
    report: dict = {
        "bench": "chaos",
        "platform": "cpu",
        "docs": DOCS,
        "steps": STEPS,
        "shards": 4,
        "seeds_per_plan": args.seeds,
        "plans": {},
    }
    with tempfile.TemporaryDirectory(prefix="fluid-chaos-") as workdir:
        for name in plans:
            plan_t0 = time.time()
            if name in PROC_PLANS:
                result = run_proc_quake(args.seeds)
                result["wall_sec"] = round(time.time() - plan_t0, 3)
                report["plans"][name] = result
                print(f"{name}: {result['survived']}/"
                      f"{result['scenarios']} survived (process kills: "
                      f"{result['fault_counts']})", file=sys.stderr)
                continue
            if name in STORM_PLANS:
                runner = (run_stream_squeeze if name == "stream-squeeze"
                          else run_fold_squeeze)
                result = runner(args.seeds)
                result["wall_sec"] = round(time.time() - plan_t0, 3)
                report["plans"][name] = result
                print(f"{name}: {result['survived']}/"
                      f"{result['scenarios']} survived (storm: "
                      f"{result['storm']})", file=sys.stderr)
                continue
            result = run_plan(name, args.seeds, workdir,
                              plan_file=args.plan_file)
            result["wall_sec"] = round(time.time() - plan_t0, 3)
            report["plans"][name] = result
            print(f"{name}: {result['survived']}/{result['scenarios']} "
                  f"survived, {result['retries_per_op']} retries/op, "
                  f"p99 recovery {result['recovery_ticks_p99']} ticks",
                  file=sys.stderr)
    if not args.no_tcp:
        report["tcp_smoke"] = tcp_smoke()
        print(f"tcp_smoke: converged={report['tcp_smoke']['converged']} "
              f"demotions={report['tcp_smoke']['demotions']}",
              file=sys.stderr)
    report["total_survived"] = sum(
        p["survived"] for p in report["plans"].values())
    report["total_scenarios"] = sum(
        p["scenarios"] for p in report["plans"].values())
    report["wall_sec"] = round(time.time() - t0, 3)
    write_bench_json(report, out=args.out)


if __name__ == "__main__":
    main()
