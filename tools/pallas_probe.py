"""Standalone Pallas/Mosaic canary: the smallest highest-value TPU
measurement — did the VMEM-resident fold Mosaic-compile, and how fast is
it vs the scan on one chunk?  Runs bench._pallas_canary's subprocess
harness without the rest of the bench, so a tunnel window of a couple of
minutes still captures the round's riskiest unknown (SURVEY §7 hard-part
#4; the round-5 block-shape fix is unvalidated until this compiles on a
real chip).  Prints ONE JSON line."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def main() -> None:
    out = bench._pallas_canary()
    if out is None:  # FF_NO_PALLAS_CANARY set — no measurement was taken
        print("pallas canary disabled (FF_NO_PALLAS_CANARY)",
              file=sys.stderr)
        sys.exit(1)
    print(json.dumps({"metric": "pallas_canary", "result": out}))


if __name__ == "__main__":
    main()
