"""Service-scale bulk catch-up benchmark — the FULL container-level
north-star path (SURVEY §3.2), not just the string-kernel slice bench.py
times: ordering-service oplog → decode/plan → the product's pipelined
device fold → container summary assembly → storage upload.

Seeds N documents by driving real ContainerRuntimes through the in-proc
sequencer (the honest envelope format the service decodes), then times
ONE CatchupService.catch_up() over the whole population and verifies
sampled digests against per-doc oracle runtimes.

Prints ONE JSON line:
    {"metric": "service_bulk_catchup_ops_per_sec", "value": ..., ...}

Env knobs: SVC_DOCS (default 2048), SVC_OPS (default 96).
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.runtime.container import ContainerRuntime  # noqa: E402
from fluidframework_tpu.service.catchup import CatchupService  # noqa: E402
from fluidframework_tpu.service.orderer import LocalOrderingService  # noqa: E402

N_DOCS = int(os.environ.get("SVC_DOCS", "2048"))
OPS = int(os.environ.get("SVC_OPS", "96"))


def seed(service: LocalOrderingService):
    """N_DOCS documents, OPS string edits each, via real runtimes; returns
    {doc_id: oracle_digest} for the sampled verification."""
    digests = {}
    for d in range(N_DOCS):
        rng = random.Random(7000 + d)
        doc_id = f"doc{d}"
        ep = service.create_document(doc_id)
        runtime = ContainerRuntime()
        ds = runtime.create_datastore("ds")
        text = ds.create_channel("sequence-tpu", "text")
        runtime.connect(ep, f"c{d}")
        runtime.drain()
        service.storage.upload(doc_id, runtime.summarize(), 0)
        for _ in range(OPS):
            L = len(text.text)
            k = rng.random()
            if k < 0.62 or L == 0:
                text.insert_text(rng.randint(0, L),
                                 rng.choice(["lorem ", "ip", "x"]))
            elif k < 0.82:
                a0 = rng.randint(0, L - 1)
                text.remove_range(a0, min(L, a0 + 2))
            else:
                a0 = rng.randint(0, L - 1)
                text.annotate_range(a0, min(L, a0 + 1),
                                    {"w": rng.choice(["1", "2"])})
        runtime.drain()
        if d % 64 == 0:
            digests[doc_id] = runtime.summarize().digest()
    return digests


def main() -> None:
    t0 = time.time()
    service = LocalOrderingService()
    oracle = seed(service)
    seed_sec = time.time() - t0
    print(f"seeded {N_DOCS} docs x {OPS} ops in {seed_sec:.1f}s",
          file=sys.stderr)

    svc = CatchupService(service)
    t0 = time.time()
    handles = svc.catch_up()
    wall = time.time() - t0
    total_ops = N_DOCS * OPS
    checked = 0
    for doc_id, want in oracle.items():
        handle, _seq = handles[doc_id]
        assert service.storage.read(handle).digest() == want, doc_id
        checked += 1
    print(
        f"bulk catch-up {wall:.2f}s = {total_ops / wall:,.0f} ops/s "
        f"(device {svc.device_docs} / cpu {svc.cpu_docs} / host-ch "
        f"{svc.host_channels}); {checked} sampled digests == oracle",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "service_bulk_catchup_ops_per_sec",
        "value": round(total_ops / wall, 1),
        "unit": "ops/sec",
        "n_docs": N_DOCS,
        "ops_per_doc": OPS,
        "catchup_sec": round(wall, 3),
        "device_docs": svc.device_docs,
        "cpu_docs": svc.cpu_docs,
        "sampled_digests_ok": checked,
    }))


if __name__ == "__main__":
    main()
