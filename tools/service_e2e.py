"""Service-scale bulk catch-up benchmark — the FULL container-level
north-star path (SURVEY §3.2), not just the string-kernel slice bench.py
times: ordering-service oplog → decode/plan → the product's pipelined
device fold → container summary assembly → storage upload.

Seeds N documents by driving real ContainerRuntimes through the in-proc
sequencer (the honest envelope format the service decodes), then times
ONE CatchupService.catch_up() over the whole population and verifies
sampled digests against per-doc oracle runtimes.

Prints ONE JSON line:
    {"metric": "service_bulk_catchup_ops_per_sec", "value": ..., ...}

Env knobs: SVC_DOCS (default 2048), SVC_OPS (default 96).

``--shard-bench`` instead runs the ISSUE-7 multi-shard scenario (sharded
ordering tier under VirtualClock, mid-run shard kill, broadcaster probe)
and prints ONE JSON line with aggregate ops/sec, per-shard balance, and
p50/p99 broadcast latency in deterministic virtual ticks.  Env knobs:
SVC_SHARDS (4), SVC_SHARD_DOCS (32), SVC_SHARD_CLIENTS (2),
SVC_SHARD_STEPS (2000), SVC_SHARD_SINKS (2).
"""

from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.runtime.container import ContainerRuntime  # noqa: E402
from fluidframework_tpu.service.catchup import CatchupService  # noqa: E402
from fluidframework_tpu.service.orderer import LocalOrderingService  # noqa: E402
from fluidframework_tpu.tools.bench_harness import write_bench_json  # noqa: E402

N_DOCS = int(os.environ.get("SVC_DOCS", "2048"))
OPS = int(os.environ.get("SVC_OPS", "96"))

SHARDS = int(os.environ.get("SVC_SHARDS", "4"))
SHARD_DOCS = int(os.environ.get("SVC_SHARD_DOCS", "32"))
SHARD_CLIENTS = int(os.environ.get("SVC_SHARD_CLIENTS", "2"))
SHARD_STEPS = int(os.environ.get("SVC_SHARD_STEPS", "2000"))
SHARD_SINKS = int(os.environ.get("SVC_SHARD_SINKS", "2"))


from fluidframework_tpu.testing.load import (  # noqa: E402
    percentile as _percentile,
)


def shard_bench() -> None:
    """The multi-shard serving scenario: SHARDS orderer shards, SHARD_DOCS
    documents x SHARD_CLIENTS clients of deterministic mixed traffic with
    ONE mid-run shard kill, a serialize-once Broadcaster probe fanning
    every sequenced message to SHARD_SINKS recorder sinks per doc."""
    from fluidframework_tpu.testing.load import (ShardedLoadSpec,
                                                 run_sharded_load)

    spec = ShardedLoadSpec(
        seed=1007, shards=SHARDS, docs=SHARD_DOCS,
        clients_per_doc=SHARD_CLIENTS, steps=SHARD_STEPS,
        kill_at=SHARD_STEPS // 2, probe_sinks=SHARD_SINKS,
    )
    t0 = time.time()
    result = run_sharded_load(spec)
    wall = time.time() - t0
    lat = sorted(result.broadcast_latencies or [])
    docs_per_shard = sorted(result.shard_docs.values())
    ops_per_shard = sorted(result.shard_ops.values())
    print(
        f"sharded scenario: {result.sequenced_ops} ops across "
        f"{SHARD_DOCS} docs / {len(result.shard_docs)} surviving shards "
        f"in {wall:.2f}s; killed {result.killed_shard} "
        f"({len(result.fenced_docs)} docs re-owned, "
        f"{result.reconnects} reconnects)",
        file=sys.stderr,
    )
    write_bench_json({
        "metric": "service_shard_ops_per_sec",
        "value": round(result.sequenced_ops / wall, 1),
        "unit": "ops/sec",
        "shards": SHARDS,
        "docs": SHARD_DOCS,
        "clients_per_doc": SHARD_CLIENTS,
        "steps": SHARD_STEPS,
        "sequenced_ops": result.sequenced_ops,
        "edits": result.edits,
        "wall_sec": round(wall, 3),
        # balance over SURVIVING shards (one was killed mid-run)
        "shard_docs": result.shard_docs,
        "shard_ops": result.shard_ops,
        "doc_balance_max_over_min": (
            round(docs_per_shard[-1] / docs_per_shard[0], 2)
            if docs_per_shard and docs_per_shard[0] else None),
        "op_balance_max_over_min": (
            round(ops_per_shard[-1] / ops_per_shard[0], 2)
            if ops_per_shard and ops_per_shard[0] else None),
        # failover
        "killed_shard": result.killed_shard,
        "fenced_docs": len(result.fenced_docs),
        "reconnects": result.reconnects,
        "epoch_bumped": result.epoch_bumped,
        # broadcaster probe: serialize-once + latency in VIRTUAL ticks
        # (deterministic per seed — schedule distance, not wall time)
        "broadcast_encodes": result.broadcast_encodes,
        "broadcast_sinks_per_doc": SHARD_SINKS,
        "broadcast_deliveries": len(lat),
        "broadcast_latency_p50_ticks": _percentile(lat, 0.50),
        "broadcast_latency_p99_ticks": _percentile(lat, 0.99),
    }, compact=True)


def seed(service: LocalOrderingService):
    """N_DOCS documents, OPS string edits each, via real runtimes; returns
    {doc_id: oracle_digest} for the sampled verification."""
    digests = {}
    for d in range(N_DOCS):
        rng = random.Random(7000 + d)
        doc_id = f"doc{d}"
        ep = service.create_document(doc_id)
        runtime = ContainerRuntime()
        ds = runtime.create_datastore("ds")
        text = ds.create_channel("sequence-tpu", "text")
        runtime.connect(ep, f"c{d}")
        runtime.drain()
        service.storage.upload(doc_id, runtime.summarize(), 0)
        for _ in range(OPS):
            L = len(text.text)
            k = rng.random()
            if k < 0.62 or L == 0:
                text.insert_text(rng.randint(0, L),
                                 rng.choice(["lorem ", "ip", "x"]))
            elif k < 0.82:
                a0 = rng.randint(0, L - 1)
                text.remove_range(a0, min(L, a0 + 2))
            else:
                a0 = rng.randint(0, L - 1)
                text.annotate_range(a0, min(L, a0 + 1),
                                    {"w": rng.choice(["1", "2"])})
        runtime.drain()
        if d % 64 == 0:
            digests[doc_id] = runtime.summarize().digest()
    return digests


def main() -> None:
    t0 = time.time()
    service = LocalOrderingService()
    oracle = seed(service)
    seed_sec = time.time() - t0
    print(f"seeded {N_DOCS} docs x {OPS} ops in {seed_sec:.1f}s",
          file=sys.stderr)

    svc = CatchupService(service)
    t0 = time.time()
    handles = svc.catch_up()
    wall = time.time() - t0
    total_ops = N_DOCS * OPS
    checked = 0
    for doc_id, want in oracle.items():
        handle, _seq = handles[doc_id]
        assert service.storage.read(handle).digest() == want, doc_id
        checked += 1
    print(
        f"bulk catch-up {wall:.2f}s = {total_ops / wall:,.0f} ops/s "
        f"(device {svc.device_docs} / cpu {svc.cpu_docs} / host-ch "
        f"{svc.host_channels}); {checked} sampled digests == oracle",
        file=sys.stderr,
    )
    write_bench_json({
        "metric": "service_bulk_catchup_ops_per_sec",
        "value": round(total_ops / wall, 1),
        "unit": "ops/sec",
        "n_docs": N_DOCS,
        "ops_per_doc": OPS,
        "catchup_sec": round(wall, 3),
        "device_docs": svc.device_docs,
        "cpu_docs": svc.cpu_docs,
        "sampled_digests_ok": checked,
    }, compact=True)


if __name__ == "__main__":
    if "--shard-bench" in sys.argv[1:]:
        shard_bench()
    else:
        main()
