"""loadgen: drive fluidscale swarm scenarios and record per-scenario
perf gates (ISSUE 10).

The CLI front end of ``testing/scenarios.py``: each named scenario is a
replay-deterministic swarm — 10³ to 10⁶ columnar virtual clients whose
every op flows through the REAL sharded ordering tier's batched ingress,
the serialize-once broadcaster, and the durable op log.  A scenario only
PASSES when it sustains its ops/sec floor, its sampled documents load
byte-identical to the fault-free single-shard oracle twin, and (with
``--replay-check``) a same-seed re-run reproduces every metric and
telemetry counter bit-identically.

    python -m tools.loadgen --list
    python -m tools.loadgen --clients 1000                # quick pass
    python -m tools.loadgen --clients 100000 \
        --out BENCH_service_scale_cpu_r10.json            # the round-10 record
    python -m tools.loadgen --scenario failover-drill --replay-check
    python -m tools.loadgen --out-of-proc --clients 100000 \
        --replay-check --out BENCH_service_proc_cpu_r12.json  # round 12:
        # the REAL process tier (shard-host processes, per-shard logs,
        # front-door routing; the drill SIGKILLs a real shard process)
    python -m tools.loadgen --out-of-proc --replicas 2 --replay-check
        # round 18: every scenario through TWO shared-nothing front-door
        # replicas with the traffic-bearing one SIGKILLed mid-run
    python -m tools.loadgen --connections 100000 \
        --out BENCH_frontdoor_cpu_r18.json  # round 18: real TCP
        # connection scale against ONE event-loop front-door process,
        # RSS-tripwired per idle connection

Emits ONE JSON document via the shared bench writer: per scenario —
ops/sec (wall), p50/p99 delivery and catch-up latency in VIRTUAL ticks
(schedule distance, deterministic per seed; wall time is not), oracle
and replay verdicts (schema-stable ``null`` when skipped), counter
dumps, and the gate verdict.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fluidframework_tpu.testing.scenarios import (  # noqa: E402
    SCENARIOS, build_scenario, oracle_spec, run_swarm, scenario_docs,
)
from fluidframework_tpu.tools.bench_harness import write_bench_json  # noqa: E402

#: conservative CPU ops/sec floors per scenario (sequenced messages over
#: wall seconds, swarm + service + broadcaster + durable log end to end).
#: Measured ~30k msgs/s at 10⁵ clients on the dev container; the gate
#: trips on an order-of-magnitude regression (a Python inner loop landing
#: on the batch path), not on host jitter.
GATES_OPS_PER_SEC = {
    "steady-typing": 3000.0,
    "catchup-herd": 3000.0,
    "laggard-window": 3000.0,
    # tree changesets ride the boxed envelope path by design (outside
    # the closed columnar vocabulary), so the floor sits at the boxed
    # rate, not the columnar one.
    "tree-collab": 1000.0,
    # the storm spends its wall on REAL device folds (the whole point),
    # so its ops/sec floor sits well below the pure-ingress scenarios.
    "catchup-storm": 250.0,
    "failover-drill": 2000.0,
}

#: out-of-process floors: every op crosses the wire TWICE (swarm → front
#: door → owning shard process) and heads read back over RPC, so the
#: absolute floor is lower — the gate still trips on an order-of-magnitude
#: regression (a per-op Python loop landing on the proxy fan-out path).
GATES_OPS_PER_SEC_PROC = {
    "steady-typing": 300.0,
    "catchup-herd": 300.0,
    "laggard-window": 300.0,
    "tree-collab": 100.0,
    "catchup-storm": 100.0,
    "failover-drill": 200.0,
}

#: p99 catch-up STORM latency gate, in virtual ticks (deterministic per
#: seed): first attempt → served, across shed pacing and retries.  The
#: herd must drain in bounded schedule time, not just eventually.
STORM_GATE_P99_TICKS = 64.0

#: ISSUE 16 streaming-fold gates.  With the streaming fold attached the
#: storm must serve ≥95% of its answers with ZERO fold work (warm +
#: streaming-head lanes); the newest durable summary may trail the head
#: by at most this many fold cadences (polls run once per tick, so one
#: tick's commit burst can stack on top of the cadence); and the
#: truncated on-disk log must be strictly smaller than the untruncated
#: baseline.
STREAM_GATE_SERVE_RATE = 0.95
STREAM_GATE_LAG_CADENCES = 4.0

#: ISSUE 18 connection-scale gates (``--connections``).  The bench holds
#: N REAL TCP connections against ONE front-door process and trips when:
#: resident bytes per idle connection exceed the budget (a thread-per-
#: connection regression shows up here first — one thread stack dwarfs
#: a PumpConnection); the server's thread count scales with connections
#: instead of staying a small constant; or a sampled connection stops
#: answering ping.  The fd HEADROOM is what the two processes keep free
#: for everything that is not a herd socket (listen socket, shard RPC
#: connections, logs, stdio) — the achieved count is recorded honestly
#: against the container's NON-RAISABLE hard fd limit (``env_capped``).
CONN_FD_HEADROOM = 512
CONN_RSS_BUDGET_BYTES = 16 * 1024
CONN_MAX_SERVER_THREADS = 64
CONN_PING_SAMPLES = 64


def run_stream(seed: int, clients: int, docs: int, shards: int,
               replay_check: bool = False) -> dict:
    """The streaming-fold gate: the catchup-storm scenario twice — once
    with the sequencer-attached streaming fold ON, once OFF — over
    file-backed op logs, asserting (a) byte-identical convergence
    (heads, sampled digests, stamped counts), (b) the herd served
    almost entirely from the warm/streaming-head lanes with cold folds
    collapsed vs the OFF baseline, (c) summary lag bounded by the fold
    cadence, and (d) the on-disk log physically smaller behind the
    summary-anchored truncation."""
    import tempfile

    def _log_bytes(d: str) -> int:
        path = os.path.join(d, "swarm-ops.jsonl")
        return os.path.getsize(path) if os.path.exists(path) else 0

    spec = build_scenario("catchup-storm", seed=seed, clients=clients,
                          docs=docs, shards=shards)
    with tempfile.TemporaryDirectory(prefix="fluid-stream-") as base:
        spec_off = dataclasses.replace(spec, dir=os.path.join(base, "off"))
        spec_on = dataclasses.replace(spec, dir=os.path.join(base, "on"),
                                      stream=True)
        t0 = time.time()
        r_off = run_swarm(spec_off)
        wall_off = time.time() - t0
        t0 = time.time()
        r_on = run_swarm(spec_on)
        wall_on = time.time() - t0
        replay_identical = None
        if replay_check:
            r_on2 = run_swarm(dataclasses.replace(
                spec_on, dir=os.path.join(base, "on2")))
            replay_identical = r_on2.identity() == r_on.identity()
        bytes_off = _log_bytes(spec_off.dir)
        bytes_on = _log_bytes(spec_on.dir)

    s_off, s_on = r_off.storm, r_on.storm
    sf = s_on.get("streamfold") or {}
    converged = (r_on.per_doc_head == r_off.per_doc_head
                 and r_on.sampled_digests == r_off.sampled_digests
                 and r_on.ops_stamped == r_off.ops_stamped)
    served = int(s_on.get("served") or 0)
    no_fold = int(s_on.get("warm") or 0) + int(s_on.get("stream") or 0)
    serve_rate = round(no_fold / served, 4) if served else None
    lag_max = int(sf.get("head_lag_max") or 0)
    lag_gate = int(spec_on.stream_cadence * STREAM_GATE_LAG_CADENCES)
    # The honest before/after-truncation comparison is WITHIN the ON
    # run: final log size vs final size + the bytes compaction dropped.
    # (Comparing against the OFF run's file would charge/credit the
    # marker records and serve-pattern differences, and at small scale
    # marker overhead can exceed the reclaim — a gate artifact, not a
    # regression.)
    reclaimed = int(sf.get("oplog_bytes_reclaimed") or 0)
    untruncated_on = bytes_on + reclaimed
    passed = (
        converged
        and replay_identical is not False
        and s_on.get("served") == s_on.get("requests")
        and s_off.get("served") == s_off.get("requests")
        and serve_rate is not None and serve_rate >= STREAM_GATE_SERVE_RATE
        and lag_max <= lag_gate
        and int(sf.get("truncated_msgs") or 0) > 0
        and 0 < bytes_on < untruncated_on
    )
    return {
        "seed": seed,
        "clients": clients,
        "docs": docs,
        "shards": shards,
        "stream_cadence": spec_on.stream_cadence,
        "stream_retention": spec_on.stream_retention,
        "sequenced_ops": r_on.sequenced_ops,
        "wall_sec_on": round(wall_on, 3),
        "wall_sec_off": round(wall_off, 3),
        # steady streaming throughput: committed ops folded by the
        # streaming service per wall second of the ON run
        "stream_ops_folded_per_sec": (
            round(int(sf.get("ops_folded") or 0) / wall_on, 1)
            if wall_on > 0 else 0.0),
        # newest-durable-summary lag high-water, in sequence numbers
        # (== virtual schedule distance; nothing here reads wall clock)
        "stream_summary_lag_max_seqs": lag_max,
        "stream_lag_gate_seqs": lag_gate,
        # storm lanes, on vs off: the herd must land on warm/stream with
        # streaming attached, on warm/fold without
        "stream_serve_rate": serve_rate,
        "gate_serve_rate": STREAM_GATE_SERVE_RATE,
        "stream_serves_on": int(s_on.get("stream") or 0),
        "warm_serves_on": int(s_on.get("warm") or 0),
        "cold_folds_on": int(s_on.get("folds") or 0),
        "warm_serves_off": int(s_off.get("warm") or 0),
        "cold_folds_off": int(s_off.get("folds") or 0),
        "storm_requests": int(s_on.get("requests") or 0),
        "storm_served": served,
        # summary-anchored truncation: the ON run's final log size vs
        # what it would be without truncation (final + reclaimed); the
        # OFF run's file rides along for context only
        "oplog_bytes_off": bytes_off,
        "oplog_bytes_on": bytes_on,
        "oplog_bytes_untruncated_on": untruncated_on,
        "oplog_bytes_reclaimed": reclaimed,
        "oplog_bytes_reclaimed_ratio": (
            round(reclaimed / untruncated_on, 4)
            if untruncated_on else None),
        "truncations": int(sf.get("truncations") or 0),
        "truncated_msgs": int(sf.get("truncated_msgs") or 0),
        "converged_identical": converged,
        "replay_identical": replay_identical,
        "streamfold": sf or None,
        "passed": passed,
    }


def _proc_status(pid: int) -> dict:
    """{rss_bytes, threads} for a live pid from ``/proc`` (Linux); empty
    on platforms without procfs — the tripwire then records null and the
    gate skips the memory leg honestly instead of guessing."""
    out: dict = {}
    try:
        with open(f"/proc/{pid}/status", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    out["rss_bytes"] = int(line.split()[1]) * 1024
                elif line.startswith("Threads:"):
                    out["threads"] = int(line.split()[1])
    except OSError:
        pass
    return out


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed mid-frame")
        buf += chunk
    return bytes(buf)


def _raw_rpc(sock, method: str, params: dict, rid: int = 1):
    """One request/response round-trip on a raw herd socket, skipping any
    interleaved event frames (replies match by ``re``)."""
    import json
    import struct

    from fluidframework_tpu.protocol.wire import WIRE_VERSION, frame_bytes

    length = struct.Struct(">I")
    sock.sendall(frame_bytes({"v": WIRE_VERSION, "id": rid,
                              "method": method, "params": params}))
    while True:
        (n,) = length.unpack(_recv_exact(sock, 4))
        frame = json.loads(_recv_exact(sock, n))
        if frame.get("re") == rid:
            if not frame.get("ok"):
                raise RuntimeError(frame.get("error"))
            return frame.get("result")


def run_connections(requested: int, relay_budget: int = 4096,
                    ops: int = 256) -> dict:
    """The ISSUE 18 connection-scale gate: hold ``requested`` REAL TCP
    connections against ONE front-door process (event-loop frame pump,
    in-process shard — the bench measures the CONNECTION layer, not the
    process tier) and assert, concurrently:

    - every sampled connection still answers ``ping`` (liveness under
      load, ``CONN_PING_SAMPLES`` spread across the herd);
    - resident bytes per idle connection stay under
      ``CONN_RSS_BUDGET_BYTES`` (peak RSS over baseline / achieved);
    - the server's thread count stays a small constant
      (``CONN_MAX_SERVER_THREADS``) — the anti-thread-per-connection pin;
    - steady-typing traffic flows end to end through a real driver
      client while the herd is held; and
    - the per-connection relay byte budget is ENFORCED: a deliberately
      never-reading subscriber must be demoted (``fd.relay_demotions``
      >= 1) instead of ballooning the relay queue.

    The container's hard fd limit is not raisable from userspace, so the
    achieved count is ``min(requested, hard - CONN_FD_HEADROOM)`` and the
    report records ``env_capped`` honestly rather than silently passing a
    smaller gate.
    """
    import resource
    import shutil
    import socket
    import subprocess
    import tempfile
    import time as _time

    from fluidframework_tpu.drivers.network_driver import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.protocol.messages import (
        MessageType, RawOperation,
    )
    from fluidframework_tpu.runtime.container import ContainerRuntime

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    target = min(requested, max(1, hard - CONN_FD_HEADROOM))
    env_capped = target < requested

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = tempfile.mkdtemp(prefix="fluid-conns-")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.service.frontdoor",
         "--dir", os.path.join(base, "door"), "--shards", "1",
         "--spawn", "thread", "--port", "0", "--heartbeat", "0",
         "--relay-budget", str(relay_budget)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=repo_root)
    conns: list = []
    extra_socks: list = []
    factory = None
    try:
        host, port, pid = None, None, None
        deadline = _time.time() + 60
        while _time.time() < deadline:
            line = proc.stdout.readline()
            if line == "" and proc.poll() is not None:
                break
            if "listening on" in line:
                addr = line.split("listening on", 1)[1].split()[0]
                host, _, port_s = addr.rpartition(":")
                port = int(port_s)
                pid = int(line.rsplit("pid=", 1)[1].split()[0])
                break
        if port is None:
            raise RuntimeError("front door never reported listening")

        # Steady-typing fixture BEFORE the baseline RSS read, so the RSS
        # delta charges the herd sockets and nothing else: one real
        # driver client (reads its events) + one raw subscriber that
        # NEVER reads (the relay-budget demotion victim).
        factory = NetworkDocumentServiceFactory(host=host, port=port)
        service = factory.create_document(
            "conn-doc", ContainerRuntime().summarize())
        endpoint = service.connection()
        delivered: list = []
        endpoint.subscribe(lambda m: delivered.append(m.seq))
        endpoint.connect("typist")
        # SO_RCVBUF is clamped BEFORE connect (it fixes the negotiated
        # TCP window): otherwise loopback autotuning absorbs megabytes
        # into kernel buffers and the pump's own relay queue — the thing
        # the budget meters — never grows at bench-sized volumes.
        deadbeat = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        deadbeat.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8192)
        deadbeat.settimeout(30)
        deadbeat.connect((host, port))
        extra_socks.append(deadbeat)
        _raw_rpc(deadbeat, "subscribe_doc", {"doc": "conn-doc"})
        # From here on the deadbeat is NEVER read: broadcast bytes pile
        # into its pump-side write queue until the budget demotes it.
        baseline = _proc_status(pid)

        t0 = _time.time()
        rss_peak = baseline.get("rss_bytes", 0)
        for i in range(target):
            for attempt in (1, 2, 3):
                try:
                    conns.append(
                        socket.create_connection((host, port), timeout=30))
                    break
                except OSError:
                    if attempt == 3:
                        raise
                    _time.sleep(0.2)  # accept burst backlog: brief, rare
            if (i + 1) % 2048 == 0:
                rss_peak = max(rss_peak, _proc_status(pid)
                               .get("rss_bytes", 0))
            if (i + 1) % 8192 == 0:
                print(f"  connections: {i + 1}/{target}", file=sys.stderr)
        connect_wall = _time.time() - t0

        ping_ok = 0
        stride = max(1, target // CONN_PING_SAMPLES)
        sampled = list(range(0, target, stride))
        for j in sampled:
            if _raw_rpc(conns[j], "ping", {}) == "pong":
                ping_ok += 1

        # Traffic while the herd is held: real ops through the driver,
        # events delivered back through the pump's relay path.  The
        # never-reading subscriber receives the same broadcast bytes and
        # must blow its relay budget → demotion, not unbounded queueing.
        # Only bytes the pump cannot hand to the KERNEL count against
        # the budget, and loopback autotuning absorbs megabytes before
        # send() blocks — so the bench types until the demotion fires
        # (budget enforced) or a hard byte ceiling proves it never does,
        # rather than guessing this host's kernel buffer depth.
        pad = "x" * 8192
        ops_sent, demotions = 0, 0
        stats: dict = {}
        while ops_sent < ops or (not demotions and ops_sent < 2048):
            endpoint.submit(RawOperation(
                client_id="typist", client_seq=ops_sent + 1, ref_seq=0,
                type=MessageType.OP,
                contents={"i": ops_sent, "pad": pad}))
            ops_sent += 1
            if ops_sent % 64 == 0:
                stats = _raw_rpc(conns[0], "stats", {})
                demotions = stats["counters"].get("fd.relay_demotions", 0)
        deadline = _time.time() + 30
        while _time.time() < deadline:
            stats = _raw_rpc(conns[0], "stats", {})
            demotions = stats["counters"].get("fd.relay_demotions", 0)
            if len(delivered) >= ops_sent and demotions:
                break
            _time.sleep(0.1)
        status = _proc_status(pid)
        rss_peak = max(rss_peak, status.get("rss_bytes", 0))
        rss_base = baseline.get("rss_bytes")
        per_conn = (max(0, rss_peak - rss_base) / target
                    if rss_base is not None else None)
        threads = status.get("threads")
        pump = stats.get("pump") or {}
        passed = (
            len(conns) == target
            and ping_ok == len(sampled)
            and pump.get("open", 0) >= target
            and (per_conn is None or per_conn <= CONN_RSS_BUDGET_BYTES)
            and (threads is None or threads <= CONN_MAX_SERVER_THREADS)
            and len(delivered) >= ops_sent
            and demotions >= 1
        )
        return {
            "requested_connections": requested,
            "achieved_connections": len(conns),
            "fd_hard_limit": hard,
            "fd_headroom": CONN_FD_HEADROOM,
            "env_capped": env_capped,
            "connect_wall_sec": round(connect_wall, 3),
            "connects_per_sec": (round(target / connect_wall, 1)
                                 if connect_wall > 0 else None),
            "rss_baseline_bytes": rss_base,
            "rss_peak_bytes": rss_peak,
            "rss_per_conn_bytes": (round(per_conn, 1)
                                   if per_conn is not None else None),
            "rss_budget_per_conn_bytes": CONN_RSS_BUDGET_BYTES,
            "server_threads": threads,
            "server_threads_max": CONN_MAX_SERVER_THREADS,
            "ping_sampled": len(sampled),
            "ping_ok": ping_ok,
            "ops_submitted": ops_sent,
            "events_delivered": len(delivered),
            "relay_budget_bytes": relay_budget,
            "relay_demotions": demotions,
            "pump": pump or None,
            "passed": passed,
        }
    finally:
        if factory is not None:
            try:
                factory.close()
            except Exception:
                pass
        for sock in conns + extra_socks:
            try:
                sock.close()
            except OSError:
                pass
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
        shutil.rmtree(base, ignore_errors=True)


def run_one(name: str, seed: int, clients: int, docs: int, shards: int,
            oracle: bool, replay_check: bool, columnar: bool = True,
            sample_every: int = 8, gate_override: float = None,
            compare_boxed: bool = False, out_of_proc: bool = False,
            replicas: int = 1) -> dict:
    spec = build_scenario(name, seed=seed, clients=clients, docs=docs,
                          shards=shards)
    if out_of_proc and name == "catchup-storm":
        # The catchup.* seams live inside the shard processes, which
        # scheduled-site validation rightly rejects from the harness
        # plan; the deterministic in-proc storm is the seam-coverage
        # run — out of proc exercises the real RPC path instead, and
        # (ISSUE 18) WIDENS the real-call sample: connections are cheap
        # behind the event-loop pump, so 4× the storming clients per doc
        # actually cross the wire.
        spec = dataclasses.replace(spec, plan=None,
                                   storm_clients_per_doc=16)
    if out_of_proc and name == "failover-drill":
        # The drill's scheduled kill becomes a REAL process kill: same
        # tick, same victim selection, SIGKILL semantics.
        from fluidframework_tpu.testing.faults import FaultPlan, FaultPoint

        spec = dataclasses.replace(spec, plan=FaultPlan(
            seed=seed, points=tuple(
                FaultPoint("proc.kill", "kill", at=p.at, doc=p.doc,
                           shard=p.shard)
                for p in spec.plan.points if p.site == "shard.kill")))
    if out_of_proc and replicas > 1:
        # ISSUE 18 replica drill: run the scenario through N shared-
        # nothing front-door replicas and SIGKILL the traffic-bearing
        # one mid-run — client drivers fail over through the survivor
        # and the single-replica oracle twin must still match
        # byte-identically (the twin resets replicas=1 and drops the
        # kill, so the verdict is the failover's, not the topology's).
        from fluidframework_tpu.testing.faults import FaultPlan, FaultPoint

        mid = max(1, sum(p.ticks for p in spec.phases) // 2)
        points = tuple(spec.plan.points) if spec.plan is not None else ()
        # out_of_proc rides along here (it is re-applied below): the
        # replicas>1 spec validation rightly refuses an in-proc topology.
        spec = dataclasses.replace(spec, replicas=replicas,
                                   out_of_proc=True, plan=FaultPlan(
                                       seed=seed, points=points + (
                                           FaultPoint("replica.kill",
                                                      "kill", at=mid),)))
    spec = dataclasses.replace(spec, columnar=columnar,
                               sample_every=sample_every,
                               out_of_proc=out_of_proc,
                               # catchup-herd and tree-collab are the
                               # fold-tier scenarios: after the swarm run
                               # their sampled docs catch up cold+warm
                               # through the REAL CatchupService so the
                               # report carries the resident-tier
                               # counters (ISSUE 13) — served / spliced /
                               # evictions / bytes_saved next to delta +
                               # pack stats — and, for tree-collab, the
                               # SECOND kernel family's tree-tier
                               # counters (ISSUE 14).
                               fold_probe=(
                                   name in ("catchup-herd", "tree-collab")
                                   and not out_of_proc))
    t0 = time.time()
    result = run_swarm(spec)
    wall = time.time() - t0  # the gated number times the PRIMARY run only
    oracle_match = None
    if oracle:
        twin = run_swarm(oracle_spec(spec, result))
        oracle_match = (result.sampled_digests == twin.sampled_digests
                        and result.per_doc_head == twin.per_doc_head)
    replay_identical = None
    if replay_check:
        replay_identical = \
            run_swarm(spec).identity() == result.identity()
    boxed_compare = None
    if compare_boxed:
        # The r10 ingress comparator: the SAME scenario through the boxed
        # per-op path (parity-pinned byte-identical), so the recorded
        # ingress_us_per_op ratio is apples to apples.
        t0 = time.time()
        boxed = run_swarm(dataclasses.replace(spec, columnar=False))
        boxed_wall = time.time() - t0
        speedup = (boxed.ingress["ingress_us_per_op"]
                   / result.ingress["ingress_us_per_op"]
                   if result.ingress["ingress_us_per_op"] else None)
        boxed_compare = {
            "identity_match": boxed.identity() == result.identity(),
            "ops_per_sec": round(boxed.sequenced_ops / boxed_wall, 1)
            if boxed_wall > 0 else 0.0,
            "ingress": boxed.ingress,
            "ingress_speedup_vs_boxed":
                round(speedup, 2) if speedup else None,
        }
    storm_report = None
    if spec.storm:
        storm = result.storm
        tiers = storm.get("tiers") or {}
        cache = tiers.get("cache") or {}
        lookups = cache.get("hits", 0) + cache.get("misses", 0)
        admission = storm.get("admission") or {}
        # The ISSUE-15 acceptance balance: every fold-lane entry is
        # accounted — admitted + shed + degraded = requests (warm
        # bypasses ride outside the balance by design).
        balance_ok = (
            admission.get("catchup.requests", 0)
            == admission.get("catchup.admitted", 0)
            + admission.get("catchup.shed", 0)
            + admission.get("catchup.degraded", 0)
        ) if admission else None
        coverage_ok = (all(
            result.fault_counts.get(f"{p.site}:{p.kind}", 0) > 0
            for p in spec.plan.points
        ) if spec.plan is not None else None)
        p99 = storm.get("latency_p99_ticks")
        storm_report = {
            **{key: storm.get(key) for key in (
                "mode", "requests", "served", "warm", "folds", "shed",
                "degraded", "retries", "fold_errors", "shed_rate",
                "latency_p50_ticks", "latency_p99_ticks",
                "latency_samples")},
            # Fraction of storm answers served with ZERO fold work (the
            # warm priority lane: tier-0/1 serves, single-flight joins,
            # and the no-new-ops fast path — the last bypasses the
            # tier-1 hit counter, so this is the honest storm-side rate;
            # the raw tier-1 lookup split stays under "tiers").
            "cache_hit_rate": (
                round(storm.get("warm", 0) / storm["served"], 4)
                if storm.get("served") else None),
            "tier1_lookup_hit_rate": (
                round(cache.get("hits", 0) / lookups, 4)
                if lookups else None),
            "degraded_serves": storm.get("degraded"),
            "admission": admission or None,
            "admission_balance_ok": balance_ok,
            "fault_coverage_ok": coverage_ok,
            "gate_p99_ticks": STORM_GATE_P99_TICKS,
            "tiers": tiers or None,
        }
    ops_per_sec = result.sequenced_ops / wall if wall > 0 else 0.0
    gate = (gate_override if gate_override is not None
            else (GATES_OPS_PER_SEC_PROC if out_of_proc
                  else GATES_OPS_PER_SEC).get(name))
    passed = (
        (gate is None or ops_per_sec >= gate)
        and oracle_match is not False
        and replay_identical is not False
        and (boxed_compare is None or boxed_compare["identity_match"])
        and (storm_report is None or (
            storm_report["served"] == storm_report["requests"]
            and storm_report["admission_balance_ok"] is not False
            and storm_report["fault_coverage_ok"] is not False
            and (storm_report["latency_p99_ticks"] is None
                 or storm_report["latency_p99_ticks"]
                 <= STORM_GATE_P99_TICKS)))
    )
    return {
        "clients": result.clients,
        "docs": result.docs,
        "shards": result.shards,
        "replicas": replicas if out_of_proc else 1,
        "ticks": result.ticks,
        "seed": seed,
        "sequenced_ops": result.sequenced_ops,
        "ops_stamped": result.ops_stamped,
        "ops_deduped": result.ops_deduped,
        "joins": result.joins,
        "ops_per_sec": round(ops_per_sec, 1),
        "gate_ops_per_sec": gate,
        "wall_sec": round(wall, 3),
        # latency in VIRTUAL ticks: deterministic per seed
        "delivery_p50_ticks": result.delivery_p50_ticks,
        "delivery_p99_ticks": result.delivery_p99_ticks,
        "delivery_samples": result.delivery_samples,
        "catchup_p50_ticks": result.catchup_p50_ticks,
        "catchup_p99_ticks": result.catchup_p99_ticks,
        "catchup_samples": result.catchup_samples,
        "max_pending_depth": result.max_pending_depth,
        "defers": len(result.defers),
        "join_defers": len(result.join_defers),
        "kills": [list(k) for k in result.kills],
        "sampled_docs": len(result.sampled_digests),
        # schema-stable verdicts: null when the check was skipped
        "oracle_match": oracle_match,
        "replay_identical": replay_identical,
        "fault_counts": result.fault_counts,
        "counters": result.counters,
        # ingress-stage accounting (wall-derived; outside replay identity)
        "columnar": columnar,
        "ingress": result.ingress,
        "boxed_compare": boxed_compare,
        # out-of-proc: per-shard counters over the stats RPC + live-tap
        # delivery audit (empty dict for in-proc runs)
        "out_of_proc": out_of_proc,
        "shard_stats": result.shard_stats,
        # catchup-herd: resident / delta / pack fold-tier counters from
        # the post-run cold+warm CatchupService pass over sampled docs
        # (empty dict on other scenarios)
        "fold_tier": result.fold_tier,
        # catchup-storm: the herd-through-the-real-fold-tier record —
        # lanes, shed rate, cache hit rate, degraded serves, gated p99
        # storm latency, admission balance + fault-coverage verdicts
        "storm": storm_report,
        "passed": passed,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="drive fluidscale swarm scenarios with perf gates")
    parser.add_argument("--list", action="store_true",
                        help="print named scenarios with one-line docs")
    parser.add_argument("--scenario", choices=tuple(SCENARIOS) + ("all",),
                        default="all")
    parser.add_argument("--clients", type=int, default=100_000)
    parser.add_argument("--docs", type=int, default=128)
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--seed", type=int, default=10)
    parser.add_argument("--no-oracle", action="store_true",
                        help="skip the single-shard oracle twin "
                             "(halves the wall time; verdict is null)")
    parser.add_argument("--replay-check", action="store_true",
                        help="re-run each scenario with the same seed and "
                             "assert bit-identical metrics + counters")
    parser.add_argument("--boxed", action="store_true",
                        help="drive the per-op boxed ingress path instead "
                             "of the columnar wire path (the r10 shape)")
    parser.add_argument("--sample-every", type=int, default=8,
                        help="sample every Nth document for elections + "
                             "the digest oracle (sampled docs keep live "
                             "broadcast subscribers and pay per-message "
                             "materialization)")
    parser.add_argument("--gate", type=float, default=None,
                        help="override the per-scenario ops/sec floor "
                             "(e.g. 100000 for the 10^6-client record)")
    parser.add_argument("--compare-boxed", action="store_true",
                        help="re-run each scenario through the boxed path "
                             "and record the ingress_us_per_op ratio "
                             "(plus a full identity parity verdict)")
    parser.add_argument("--storm", action="store_true",
                        help="run the catchup-storm scenario as THE gate "
                             "(ISSUE 15): a join herd through the REAL "
                             "catchup RPC with adaptive admission — "
                             "records cache_hit_rate, shed_rate, "
                             "degraded_serves, gated p99 storm latency, "
                             "admission balance and fault coverage")
    parser.add_argument("--stream", action="store_true",
                        help="run the streaming-fold gate (ISSUE 16): "
                             "catchup-storm with the sequencer-attached "
                             "streaming fold on vs off — byte-identical "
                             "convergence, ≥95%% zero-fold serves, "
                             "cadence-bounded summary lag, and the "
                             "truncated log strictly smaller on disk")
    parser.add_argument("--out-of-proc", action="store_true",
                        help="drive the REAL process tier: shard-host "
                             "processes with per-shard durable logs behind "
                             "the routing front door (ISSUE 12); the "
                             "failover drill SIGKILLs a real shard process")
    parser.add_argument("--replicas", type=int, default=1,
                        help="front-door replicas for out-of-proc runs "
                             "(ISSUE 18); with >= 2 the traffic-bearing "
                             "replica is SIGKILLed mid-run and clients "
                             "fail over through a survivor")
    parser.add_argument("--connections", type=int, default=None,
                        help="connection-scale gate (ISSUE 18): hold N "
                             "REAL TCP connections against one event-loop "
                             "front-door process under a per-connection "
                             "RSS tripwire, with steady-typing traffic "
                             "flowing and relay budgets enforced; the "
                             "achieved count is capped by the container's "
                             "hard fd limit and recorded honestly")
    parser.add_argument("--out", default=None,
                        help="write the JSON report here (default stdout)")
    args = parser.parse_args(argv)

    if args.list:
        for name, doc in scenario_docs().items():
            print(f"{name:16s} {doc}")
        return 0

    if args.connections:
        t0 = time.time()
        result = run_connections(args.connections)
        report = {
            "bench": "frontdoor_connections",
            "platform": "cpu",
            "connections": result,
            "wall_sec": round(time.time() - t0, 3),
        }
        print(
            f"connections: {result['achieved_connections']}/"
            f"{result['requested_connections']}"
            f"{' (env fd cap)' if result['env_capped'] else ''} | "
            f"{result['rss_per_conn_bytes']}B/conn rss "
            f"(budget {result['rss_budget_per_conn_bytes']}) | "
            f"threads {result['server_threads']} | ping "
            f"{result['ping_ok']}/{result['ping_sampled']} | events "
            f"{result['events_delivered']}/{result['ops_submitted']} | "
            f"demotions {result['relay_demotions']} | "
            f"{'PASS' if result['passed'] else 'FAIL'}",
            file=sys.stderr,
        )
        write_bench_json(report, out=args.out)
        return 0 if result["passed"] else 1

    if args.stream:
        t0 = time.time()
        result = run_stream(args.seed, args.clients, args.docs,
                            args.shards, replay_check=args.replay_check)
        report = {
            "bench": "streamfold",
            "platform": "cpu",
            "clients": args.clients,
            "docs": args.docs,
            "shards": args.shards,
            "stream": result,
            "wall_sec": round(time.time() - t0, 3),
        }
        print(
            f"streamfold: folds {result['cold_folds_off']}→"
            f"{result['cold_folds_on']} | serve_rate "
            f"{result['stream_serve_rate']} | lag "
            f"{result['stream_summary_lag_max_seqs']}/"
            f"{result['stream_lag_gate_seqs']} seqs | log "
            f"{result['oplog_bytes_untruncated_on']}→"
            f"{result['oplog_bytes_on']}B | "
            f"converged={result['converged_identical']} | "
            f"{'PASS' if result['passed'] else 'FAIL'}",
            file=sys.stderr,
        )
        write_bench_json(report, out=args.out)
        return 0 if result["passed"] else 1

    if args.storm:
        args.scenario = "catchup-storm"
    names = tuple(SCENARIOS) if args.scenario == "all" else (args.scenario,)
    t0 = time.time()
    report: dict = {
        "bench": ("catchup_storm" if args.storm
                  else "service_proc" if args.out_of_proc
                  else "service_scale"),
        "platform": "cpu",
        "clients": args.clients,
        "docs": args.docs,
        "shards": args.shards,
        "columnar": not args.boxed,
        "sample_every": args.sample_every,
        "out_of_proc": args.out_of_proc,
        "replicas": args.replicas if args.out_of_proc else 1,
        "scenarios": {},
    }
    for name in names:
        result = run_one(name, args.seed, args.clients, args.docs,
                         args.shards, oracle=not args.no_oracle,
                         replay_check=args.replay_check,
                         columnar=not args.boxed,
                         sample_every=args.sample_every,
                         gate_override=args.gate,
                         compare_boxed=args.compare_boxed,
                         out_of_proc=args.out_of_proc,
                         replicas=args.replicas)
        report["scenarios"][name] = result
        print(
            f"{name}: {result['sequenced_ops']} msgs @ "
            f"{result['ops_per_sec']:,.0f}/s | ingress "
            f"{result['ingress']['ingress_us_per_op']}us/op | delivery p99 "
            f"{result['delivery_p99_ticks']} ticks | catchup p99 "
            f"{result['catchup_p99_ticks']} ticks | oracle="
            f"{result['oracle_match']} replay={result['replay_identical']} "
            f"| {'PASS' if result['passed'] else 'FAIL'}",
            file=sys.stderr,
        )
    report["total_passed"] = sum(
        1 for s in report["scenarios"].values() if s["passed"])
    report["total_scenarios"] = len(report["scenarios"])
    report["wall_sec"] = round(time.time() - t0, 3)
    write_bench_json(report, out=args.out)
    return 0 if report["total_passed"] == report["total_scenarios"] else 1


if __name__ == "__main__":
    sys.exit(main())
